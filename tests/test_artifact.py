"""Sealed model artifacts (doc/artifacts.md): program registry,
``task = export`` bundles, near-zero-cold-start serve boot.

The contract under test:

- ``task = export`` writes a two-phase-committed bundle (verified
  snapshot + serialized executables + fingerprinted manifest) that
  ``ckpt_verify`` vouches for, and any tampered byte — including
  inside a serialized executable — fails verification with exit 1.
- Booting serve from a bundle on a matching runtime produces ZERO
  compile events (warmup included) and parity-identical outputs vs a
  snapshot boot; the ``artifact_load`` record counts every program as
  a hit.
- A mismatched fingerprint falls back per-key to re-lower+compile
  with exactly ONE warning — and still serves identical outputs.
- The hot-swap watcher picks up new verified bundles and prefers a
  bundle over a snapshot at the same counter.
"""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))

from cxxnet_tpu.artifact import registry as areg
from cxxnet_tpu.artifact import bundle as ab
from cxxnet_tpu.main import LearnTask
from cxxnet_tpu.monitor import MemorySink, Monitor
from cxxnet_tpu.monitor.schema import validate_records
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.parallel import make_mesh
from cxxnet_tpu.utils.config import parse_config
from cxxnet_tpu.utils.faultfs import FaultFS

SYNTH = """
netconfig=start
layer[+1:h] = fullc:fc1
  nhidden = 16
  init_sigma = 0.05
layer[+1] = relu
layer[h->o] = fullc:fc2
  nhidden = 4
  init_sigma = 0.05
layer[+0] = softmax
netconfig=end
input_shape = 1,1,24
batch_size = 8
eta = 0.1
"""

CFG = parse_config(SYNTH)


@pytest.fixture
def faultfs():
    fs = FaultFS("fault").install()
    try:
        yield fs
    finally:
        fs.uninstall()


def _snapshot(tmp_path, name="0001.model.npz"):
    t = NetTrainer(CFG, mesh=make_mesh(1, 1))
    t.init_model()
    path = str(tmp_path / name)
    t.save_model(path)
    return path


def _export(tmp_path, snap, out=""):
    conf = str(tmp_path / "run.conf")
    with open(conf, "w") as f:
        f.write(SYNTH)
    argv = [conf, "task=export", "model_in=%s" % snap]
    if out:
        argv.append("export_out=%s" % out)
    assert LearnTask().run(argv) == 0
    return out or ab.default_bundle_path(snap)


@pytest.fixture(scope="module")
def exported(tmp_path_factory):
    """One snapshot + committed bundle shared by the read-only tests
    (export costs ~6 program compiles; pay it once)."""
    tmp_path = tmp_path_factory.mktemp("artifact")
    snap = _snapshot(tmp_path)
    bundle = _export(tmp_path, snap)
    return tmp_path, snap, bundle


# -- key scheme -----------------------------------------------------------


def test_registry_keys_roundtrip_via_repr():
    """Bundle manifests encode registry keys as repr; literal_eval
    must recover them exactly — for every kind's sig shape."""
    keys = [
        ("pred",) + areg.pred_sig((8, 24), np.dtype(np.float32), True,
                                  0, (5,)),
        ("update",) + areg.update_sig((8, 24), "float32", (8, 1),
                                      False, 0, True),
        ("update_many",) + areg.update_many_sig(
            (4, 8, 24), "uint8", (4, 8, 1), True, 0, 4, False),
        ("run_steps",) + areg.run_steps_sig((8, 24), "bfloat16",
                                            (8, 1), True, 0, 200),
    ]
    for key in keys:
        assert areg.parse_key(repr(key)) == key
    with pytest.raises(ValueError):
        areg.parse_key("'not-a-key-tuple'")


def test_trainer_dispatch_sigs_match_precompile_keys():
    """The single-sourcing claim, mechanically: a precompile()d
    trainer dispatches every steady-state program as an AOT hit —
    its runtime signatures resolve to the registry keys precompile
    built (a scheme drift would strand dispatch on jit fallback)."""
    from cxxnet_tpu.io.data import DataBatch
    t = NetTrainer(CFG, mesh=make_mesh(1, 1))
    t.init_model()
    n = t.precompile(window=2)
    assert n > 0 and len(t.programs) == n
    rng = np.random.RandomState(0)

    def batch():
        return DataBatch(
            data=rng.rand(8, 24).astype(np.float32),
            label=rng.randint(0, 4, (8, 1)).astype(np.float32))

    sink = MemorySink()
    t.set_monitor(Monitor(sink))
    t.update(batch())
    t.update_many([batch(), batch()])
    steps = [r for r in sink.records if r["event"] == "step"]
    assert steps and not any(r["compile"] for r in steps)


# -- export + verification ------------------------------------------------


def test_export_writes_committed_verified_bundle(exported):
    _, snap, bundle = exported
    rep = ab.verify_bundle(bundle)
    assert rep["ok"], rep["error"]
    assert rep["programs"] > 0
    man = ab.bundle_manifest(bundle)
    assert man["buckets"] == [1, 2, 4, 8]
    assert man["fingerprint"] == ab.runtime_fingerprint(
        make_mesh(1, 1))
    # every member row carries a digest; the commit marker vouches
    # for the manifest bytes themselves
    assert all(m["sha256"] for m in man["members"])
    assert os.path.exists(
        os.path.join(bundle, ab.MANIFEST_NAME + ab.OK_SUFFIX))


def test_default_bundle_path_convention():
    assert ab.default_bundle_path("/m/0042.model.npz") \
        == "/m/0042.model.bundle"
    # a bundle model_in re-exports IN PLACE: .bundle.bundle would be
    # invisible to the watcher's BUNDLE_RE forever
    assert ab.default_bundle_path("/m/0042.model.bundle") \
        == "/m/0042.model.bundle"
    assert ab.default_bundle_path("/m/0042.model.bundle/") \
        == "/m/0042.model.bundle"


def test_commit_marker_sha_is_required(exported, tmp_path_factory):
    """A marker rewritten without file_sha256 (the consistent-rewrite
    tamper class) must fail verification, not pass leniently."""
    import shutil
    _, _, bundle = exported
    clone = str(tmp_path_factory.mktemp("marker") / "0001.model.bundle")
    shutil.copytree(bundle, clone)
    okp = os.path.join(clone, ab.MANIFEST_NAME + ab.OK_SUFFIX)
    marker = json.load(open(okp))
    del marker["file_sha256"]
    with open(okp, "w") as f:
        json.dump(marker, f)
    rep = ab.verify_bundle(clone)
    assert not rep["ok"] and "file_sha256" in rep["error"]


def test_consistently_rewritten_manifest_bad_rows_report(
        exported, tmp_path_factory):
    """A manifest rewritten CONSISTENTLY with its marker but holding
    a non-string member name must come back as a verdict (and be
    skipped by the watcher scan), never a TypeError from the path
    join — the report-don't-raise contract for every tamper shape."""
    import hashlib
    import shutil
    _, _, bundle = exported
    clone = str(tmp_path_factory.mktemp("rows") / "0001.model.bundle")
    shutil.copytree(bundle, clone)
    manp = os.path.join(clone, ab.MANIFEST_NAME)
    man = json.load(open(manp))
    man["members"].append({"name": 5, "bytes": 1, "sha256": "x"})
    man_bytes = json.dumps(man, sort_keys=True, indent=1).encode()
    with open(manp, "wb") as f:
        f.write(man_bytes)
    with open(os.path.join(clone, ab.MANIFEST_NAME + ab.OK_SUFFIX),
              "w") as f:
        json.dump({"format_version": 1, "bytes": len(man_bytes),
                   "file_sha256":
                   hashlib.sha256(man_bytes).hexdigest()}, f)
    rep = ab.verify_bundle(clone)
    assert not rep["ok"] and "row is malformed" in rep["error"]
    with pytest.raises(ab.BundleError):
        ab.load_bundle(clone)


def test_in_place_reexport_preserves_zero_compile_boot(tmp_path):
    """Re-exporting FROM a bundle (the default in-place path) must
    pass the original serialized blobs through: a deserialized Loaded
    executable does not re-serialize faithfully (its payload comes
    back without compiled symbols), and the silent failure mode was a
    bundle that 'exports OK' but rebuilds everything at boot."""
    snap = _snapshot(tmp_path)
    bundle = _export(tmp_path, snap)
    assert _export(tmp_path, bundle) == bundle   # in place
    rep = ab.verify_bundle(bundle)
    assert rep["ok"] and rep["programs"] > 0
    rows = np.random.RandomState(1).rand(3, 24).astype(np.float32)
    sink = MemorySink()
    sess, _, summary = _serve_once(bundle, rows, Monitor(sink))
    assert [r for r in sink.records if r["event"] == "compile"] == []
    (art,) = [r for r in sink.records if r["event"] == "artifact_load"]
    assert art["hits"] == rep["programs"] and art["rebuilds"] == 0
    assert sess.warmup_programs == 0
    snap = _snapshot(tmp_path)
    conf = str(tmp_path / "run.conf")
    with open(conf, "w") as f:
        f.write(SYNTH)
    stream = str(tmp_path / "mon.jsonl")
    assert LearnTask().run([conf, "task=export", "model_in=%s" % snap,
                            "monitor=jsonl",
                            "monitor_path=%s" % stream]) == 0
    recs = [json.loads(l) for l in open(stream) if l.strip()]
    validate_records(recs)
    (exp,) = [r for r in recs if r["event"] == "export"]
    assert exp["programs"] > 0 and exp["bytes"] > 0
    assert exp["out"].endswith("0001.model.bundle")


def test_ckpt_verify_bundle_tamper_matrix(exported, capsys):
    """Any tampered byte in any member — a serialized executable, the
    snapshot, the commit marker — fails ckpt_verify with exit 1."""
    import tools.ckpt_verify as cv
    tmp_path, snap, bundle = exported
    assert cv.main([bundle]) == 0
    assert cv.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "(bundle, format v1" in out
    # tampered executable member
    prog = os.path.join(bundle, "prog-0000.pkl")
    orig = open(prog, "rb").read()
    try:
        with open(prog, "wb") as f:
            f.write(orig[:-32] + b"\0" * 32)  # same size, flipped bytes
        assert cv.main([bundle]) == 1
        assert "sha256" in capsys.readouterr().out
        assert cv.main([str(tmp_path)]) == 1   # dir scan catches it too
        capsys.readouterr()
    finally:
        with open(prog, "wb") as f:
            f.write(orig)
    # tampered snapshot inside the bundle
    sp = os.path.join(bundle, ab.SNAPSHOT_MEMBER)
    sorig = open(sp, "rb").read()
    try:
        with open(sp, "wb") as f:
            f.write(sorig[:-8])
        assert cv.main([bundle]) == 1
        capsys.readouterr()
    finally:
        with open(sp, "wb") as f:
            f.write(sorig)
    # tampered-but-parseable JSON in the commit marker: a verdict
    # (exit 1), never an AttributeError traceback — and the watcher's
    # read-only scan must survive it too (report-don't-raise)
    okp = os.path.join(bundle, ab.MANIFEST_NAME + ab.OK_SUFFIX)
    okorig = open(okp, "rb").read()
    try:
        with open(okp, "wb") as f:
            f.write(b"[]")
        rep = ab.verify_bundle(bundle)
        assert not rep["ok"] and "not a JSON object" in rep["error"]
        assert cv.main([bundle]) == 1
        capsys.readouterr()
        with pytest.raises(ab.BundleError):
            ab.load_bundle(bundle)
        from cxxnet_tpu.serve.swap import latest_verified
        c, _ = latest_verified(str(tmp_path))   # falls back to snapshot
        assert c == 1
    finally:
        with open(okp, "wb") as f:
            f.write(okorig)
    # uncommitted: explicit target fails; a dir scan reports + skips
    os.rename(okp, okp + ".bak")
    try:
        assert cv.main([bundle]) == 1
        assert "uncommitted" in capsys.readouterr().out
        assert cv.main([str(tmp_path)]) == 0
        assert "UNCOMMITTED" in capsys.readouterr().out
    finally:
        os.rename(okp + ".bak", okp)
    assert cv.main([bundle]) == 0


def test_truncated_executable_via_faultfs(tmp_path, faultfs, capsys):
    """The fault-injection path: a bundle exported to a remote store
    whose executable member suffers a torn write (truncated tail)
    must fail ckpt_verify with exit 1."""
    import tools.ckpt_verify as cv
    t = NetTrainer(CFG, mesh=make_mesh(1, 1))
    t.init_model()
    snap = str(tmp_path / "0001.model.npz")
    t.save_model(snap)
    bundle = "fault://store/0001.model.bundle"
    _export(tmp_path, snap, out=bundle)
    assert ab.verify_bundle(bundle)["ok"]
    assert cv.main([bundle]) == 0
    capsys.readouterr()
    # torn re-write of one executable member: the injected truncation
    # drops the tail bytes between write and durability
    victim = "fault://store/0001.model.bundle/prog-0001.pkl"
    data = faultfs.store[victim]
    faultfs.truncate_tail = 64
    from cxxnet_tpu.utils.stream import open_stream
    with open_stream(victim, "wb") as f:
        f.write(data)
    faultfs.clear_faults()
    rep = ab.verify_bundle(bundle)
    assert not rep["ok"] and "prog-0001" in rep["error"]
    assert cv.main([bundle]) == 1
    assert "CORRUPT" in capsys.readouterr().out


# -- the cold-start contract ----------------------------------------------


def _serve_once(model_path, rows, monitor):
    from cxxnet_tpu.serve import ServeSession
    s = ServeSession(CFG, model_path=model_path, monitor=monitor)
    out = s.predict(rows)
    summary = s.close()
    return s, out, summary


def test_bundle_boot_zero_compiles_and_parity(exported):
    """export -> boot serve from the bundle: zero compile events
    end-to-end (warmup included), every program an artifact hit, and
    outputs byte-identical to a snapshot boot."""
    _, snap, bundle = exported
    rows = np.random.RandomState(7).rand(5, 24).astype(np.float32)
    sink = MemorySink()
    sess, out_b, summary = _serve_once(bundle, rows, Monitor(sink))
    validate_records(sink.records)
    assert [r for r in sink.records if r["event"] == "compile"] == []
    assert sess.warmup_programs == 0     # nothing needed compiling
    assert summary["compile_events"] == 0
    (art,) = [r for r in sink.records if r["event"] == "artifact_load"]
    assert art["fingerprint_match"] is True
    assert art["rebuilds"] == 0
    assert art["hits"] == len(ab.bundle_manifest(bundle)["programs"]) \
        and art["hits"] > 0
    _, out_s, _ = _serve_once(snap, rows, Monitor(MemorySink()))
    assert np.array_equal(out_b, out_s)


def test_fingerprint_mismatch_rebuilds_with_one_warning(
        exported, monkeypatch):
    """A bundle sealed on a 'different' runtime: every key re-lowers
    (honest rebuild accounting), exactly ONE warning fires, and the
    served outputs are still identical — the fallback changes where
    compile time is paid, never the results."""
    _, snap, bundle = exported
    real = ab.runtime_fingerprint
    monkeypatch.setattr(
        ab, "runtime_fingerprint",
        lambda mesh=None: dict(real(mesh), jaxlib="0.0.0-elsewhere"))
    rows = np.random.RandomState(7).rand(5, 24).astype(np.float32)
    sink = MemorySink()
    sess, out_m, summary = _serve_once(bundle, rows, Monitor(sink))
    validate_records(sink.records)
    (art,) = [r for r in sink.records if r["event"] == "artifact_load"]
    nprog = len(ab.bundle_manifest(bundle)["programs"])
    assert art["fingerprint_match"] is False
    assert art["hits"] == 0 and art["rebuilds"] == nprog
    warns = [r for r in sink.records if r["event"] == "warning"
             and r["code"] == "artifact_fingerprint_mismatch"]
    assert len(warns) == 1
    # warmup re-lowered+compiled every reachable program
    compiles = [r for r in sink.records if r["event"] == "compile"]
    assert len(compiles) == nprog and sess.warmup_programs == nprog
    # post-warmup steady state is still compile-free
    assert summary["compile_events"] == 0
    monkeypatch.setattr(ab, "runtime_fingerprint", real)
    _, out_s, _ = _serve_once(snap, rows, Monitor(MemorySink()))
    assert np.array_equal(out_m, out_s)


def test_pred_boots_from_bundle(exported):
    """``model_in = <bundle>`` on the trainer path (task=pred):
    loads the inner snapshot, installs the sealed pred executables,
    and predicts identically to the snapshot."""
    from cxxnet_tpu.io.data import DataBatch
    _, snap, bundle = exported
    rows = np.random.RandomState(3).rand(8, 24).astype(np.float32)
    batch = DataBatch(data=rows,
                      label=np.zeros((8, 1), np.float32))
    tb = NetTrainer(CFG, mesh=make_mesh(1, 1))
    tb.load_model(bundle)
    assert len(tb.programs) > 0          # sealed executables resident
    ts = NetTrainer(CFG, mesh=make_mesh(1, 1))
    ts.load_model(snap)
    assert np.array_equal(tb.predict(batch), ts.predict(batch))
    # the full-bucket pred dispatch runs a bundle-installed program
    key = ("pred",) + areg.pred_sig((8, 24), np.dtype(np.float32),
                                    True, 0,
                                    (tb.graph.num_nodes - 1,))
    assert key in tb.programs


# -- hot-swap -------------------------------------------------------------


def test_watcher_flips_to_new_bundle_without_compiles(tmp_path):
    """The fleet watcher treats a newly committed bundle as a
    verified upgrade — and the shadow 'build' deserializes instead of
    compiling, so the flip skips the shadow-build compile time."""
    from cxxnet_tpu.serve import ServeSession
    from cxxnet_tpu.serve.router import ModelRouter
    from cxxnet_tpu.serve.swap import SnapshotWatcher, latest_verified
    mdir = tmp_path / "models"
    mdir.mkdir()
    snap1 = _snapshot(mdir, "0001.model.npz")
    sink = MemorySink()
    mon = Monitor(sink)
    router = ModelRouter()
    router.register("m", ServeSession(CFG, model_path=snap1,
                                      monitor=mon), 1, snap1)
    watcher = SnapshotWatcher(
        router, "m", str(mdir),
        builder=lambda p: ServeSession(CFG, model_path=p, monitor=mon),
        monitor=mon)
    assert watcher.check_once() is None  # nothing newer yet
    snap2 = _snapshot(mdir, "0002.model.npz")
    bundle2 = _export(tmp_path, snap2)
    # same counter, both verified: the bundle wins the scan
    c, path = latest_verified(str(mdir))
    assert c == 2 and path == bundle2
    sink.clear()
    rec = watcher.check_once()
    assert rec is not None and rec["new_counter"] == 2
    assert rec["path"] == bundle2
    # the shadow build paid zero compiles: every program deserialized
    assert [r for r in sink.records if r["event"] == "compile"] == []
    (art,) = [r for r in sink.records if r["event"] == "artifact_load"]
    assert art["hits"] > 0 and art["rebuilds"] == 0
    assert rec["warmup_programs"] == 0
    router.close_all(drain=True)


def test_watcher_same_counter_snapshot_to_bundle_upgrade(tmp_path):
    """The headline deploy loop: the fleet serves NNNN.model.npz and
    an export seals NNNN.model.bundle beside it. The watcher must
    upgrade to the bundle at the SAME counter (and not flap back and
    forth afterwards)."""
    from cxxnet_tpu.serve import ServeSession
    from cxxnet_tpu.serve.router import ModelRouter
    from cxxnet_tpu.serve.swap import SnapshotWatcher
    mdir = tmp_path / "models"
    mdir.mkdir()
    snap1 = _snapshot(mdir, "0001.model.npz")
    mon = Monitor(MemorySink())
    router = ModelRouter()
    router.register("m", ServeSession(CFG, model_path=snap1,
                                      monitor=mon), 1, snap1)
    watcher = SnapshotWatcher(
        router, "m", str(mdir),
        builder=lambda p: ServeSession(CFG, model_path=p, monitor=mon),
        monitor=mon)
    assert watcher.check_once() is None
    bundle1 = _export(tmp_path, snap1)
    rec = watcher.check_once()
    assert rec is not None and rec["new_counter"] == 1
    assert rec["path"] == bundle1 and rec["warmup_programs"] == 0
    # stable afterwards: already on the bundle, no repeat swap
    assert watcher.check_once() is None
    assert router.resolve("m").path == bundle1
    router.close_all(drain=True)


# -- serve_bench cold-start column ----------------------------------------


def test_serve_bench_artifact_cold_start_record(exported, tmp_path,
                                                capsys):
    import tools.serve_bench as sb
    _, snap, bundle = exported
    out = str(tmp_path / "SB.json")
    rc = sb.main(["--artifact", bundle, "--clients", "1",
                  "--requests", "4", "--out", out])
    assert rc == 0
    rec = json.load(open(out))
    assert rec["zero_recompiles"]
    (cold,) = rec["cold_start"]
    assert cold["via"] == "artifact"
    assert cold["compile_events"] == 0
    assert cold["warmup_programs"] == 0
    assert cold["artifact_hits"] > 0 and cold["artifact_rebuilds"] == 0
    assert cold["fingerprint_match"] is True
    assert cold["time_to_first_reply_s"] > 0
    capsys.readouterr()

"""Crash-safe checkpointing (nnet/checkpoint.py, doc/checkpointing.md):
the fault matrix, end-to-end.

Every failure mode the subsystem claims to survive is injected here —
torn local commits, zero-byte/truncated snapshots handed to continue=1,
ENOSPC mid-serialize, digest corruption, a manifest-less remote payload
(the remote torn commit), SIGTERM mid-round — plus the positive paths:
async commit overlap, retention GC, format-version gating, stream
retries, multi-rank root-only writes, and the offline verifier tool.
"""

import io
import json
import os
import signal
import threading

import numpy as np
import pytest

import jax

from cxxnet_tpu.main import EXIT_PREEMPTED, main
from cxxnet_tpu.monitor import MemorySink, Monitor, set_global
from cxxnet_tpu.monitor.schema import read_jsonl, validate_records
from cxxnet_tpu.nnet.checkpoint import (CheckpointManager,
                                        SnapshotFormatError,
                                        SnapshotIntegrityError,
                                        compute_digest,
                                        find_latest_valid,
                                        read_snapshot, retention_sweep,
                                        scan_snapshots, verify_snapshot,
                                        write_snapshot)
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.utils.config import parse_config
from cxxnet_tpu.utils.faultfs import FaultFS
from cxxnet_tpu.utils.stream import (open_stream, register_scheme,
                                     set_stream_retry)
from tests.test_trainer import MLP_CONF, make_iters, make_trainer, \
    synth_idx


@pytest.fixture
def faultfs():
    fs = FaultFS("fault").install()
    try:
        yield fs
    finally:
        fs.uninstall()


@pytest.fixture(autouse=True)
def _reset_retry():
    yield
    set_stream_retry(0)
    set_global(None)


def trained_trainer(tmp_path):
    tr, te = make_iters(tmp_path)
    t = make_trainer()
    for batch in tr:
        t.update(batch)
    tr.close()
    te.close()
    return t


def write_conf(tmp_path, model_dir=None, extra=""):
    pimg, plab = synth_idx(str(tmp_path), n=200, name="tr")
    conf = """
data = train
iter = mnist
  path_img = "%s"
  path_label = "%s"
  silent = 1
iter = end
%s
input_shape = 1,1,256
batch_size = 50
eta = 0.1
metric[label] = error
num_round = 2
save_model = 1
model_dir = "%s"
print_step = 0
eval_train = 0
%s
""" % (pimg, plab, MLP_CONF.split("input_shape")[0],
       model_dir or str(tmp_path / "models"), extra)
    p = str(tmp_path / "ckpt_run.conf")
    with open(p, "w") as f:
        f.write(conf)
    return p


# -- atomic local commit --------------------------------------------------


def test_save_is_atomic_and_digested(tmp_path):
    t = trained_trainer(tmp_path)
    path = str(tmp_path / "m" / "0001.model.npz")
    t.save_model(path)
    assert os.path.exists(path)
    assert not os.path.exists(path + ".tmp")
    blob = dict(np.load(path, allow_pickle=False))
    meta = json.loads(bytes(blob["__meta__"]).decode())
    assert meta["format_version"] == 2
    assert meta["content_digest"] == compute_digest(blob)
    # and the verified loader round-trips it
    t2 = NetTrainer(parse_config(MLP_CONF))
    t2.load_model(path)
    assert t2.update_counter == t.update_counter


def test_kill_between_tmp_write_and_rename_is_invisible(tmp_path):
    """A kill -9 between the tmp write and the rename leaves only a
    .tmp sibling; resume never sees it and the scan sweeps it."""
    t = trained_trainer(tmp_path)
    mdir = str(tmp_path / "m")
    t.save_model(os.path.join(mdir, "0001.model.npz"))
    # the torn state a kill leaves: a partial tmp for the NEXT counter
    tmp = os.path.join(mdir, "0002.model.npz.tmp")
    with open(os.path.join(mdir, "0001.model.npz"), "rb") as f:
        partial = f.read()[:1000]
    with open(tmp, "wb") as f:
        f.write(partial)
    rep = find_latest_valid(mdir)
    assert rep.counter == 1
    assert rep.quarantined == []
    assert not os.path.exists(tmp)       # stale tmp swept


def test_continue_skips_zero_byte_and_truncated_newest(tmp_path,
                                                       capsys):
    """The pre-existing _latest_snapshot crash (ISSUE 5 satellite 1):
    continue=1 must never hand an unvalidated path to load_model."""
    conf = write_conf(tmp_path)
    assert main([conf]) == 0
    mdir = tmp_path / "models"
    good = sorted(os.listdir(mdir))
    assert good == ["0001.model.npz", "0002.model.npz"]
    # a crash mid-write under the OLD writer: zero-byte + truncated
    (mdir / "0003.model.npz").write_bytes(b"")
    (mdir / "0004.model.npz").write_bytes(
        (mdir / "0002.model.npz").read_bytes()[:512])
    assert main([conf, "continue=1", "num_round=4"]) == 0
    names = sorted(os.listdir(mdir))
    # resumed from 0002 (rounds 3 and 4 trained and re-committed
    # fresh 0003/0004 snapshots), corpses quarantined out of the way
    assert "0003.model.npz.quarantined" in names
    assert "0004.model.npz.quarantined" in names
    for n in ("0003.model.npz", "0004.model.npz"):
        assert verify_snapshot(str(mdir / n))["ok"]
    err = capsys.readouterr().err
    assert "quarantined" in err


def test_continue_all_corrupt_starts_fresh_with_warning(tmp_path,
                                                        capsys):
    conf = write_conf(tmp_path)
    mdir = tmp_path / "models"
    mdir.mkdir()
    (mdir / "0005.model.npz").write_bytes(b"not an npz")
    assert main([conf, "continue=1"]) == 0
    assert "0001.model.npz" in os.listdir(mdir)   # fresh from round 0
    assert "resume_no_valid_snapshot" in capsys.readouterr().err


# -- format versioning ----------------------------------------------------


def _rewrite_meta(path, mutate):
    blob = dict(np.load(path, allow_pickle=False))
    meta = json.loads(bytes(blob["__meta__"]).decode())
    mutate(meta)
    blob["__meta__"] = np.frombuffer(json.dumps(meta).encode(),
                                     np.uint8)
    with open(path, "wb") as f:
        np.savez(f, **blob)


def test_future_format_version_raises_clearly(tmp_path):
    t = trained_trainer(tmp_path)
    path = str(tmp_path / "0001.model.npz")
    t.save_model(path)
    _rewrite_meta(path, lambda m: m.update(format_version=99))
    t2 = NetTrainer(parse_config(MLP_CONF))
    with pytest.raises(SnapshotFormatError, match="format_version 99"):
        t2.load_model(path)


def test_v1_snapshot_without_digest_still_loads(tmp_path, capsys):
    """Backward direction: pre-subsystem snapshots (format_version 1,
    no content_digest) resume with a warn-once, not a crash."""
    t = trained_trainer(tmp_path)
    path = str(tmp_path / "0001.model.npz")
    t.save_model(path)
    _rewrite_meta(path, lambda m: (m.pop("content_digest"),
                                   m.update(format_version=1)))
    t2 = NetTrainer(parse_config(MLP_CONF))
    t2.load_model(path)                   # no digest -> unverified load
    assert t2.update_counter == t.update_counter
    rep = verify_snapshot(path)
    assert rep["ok"] and rep["digest"] == "missing"


# -- digest corruption ----------------------------------------------------


def _corrupt_array(path):
    blob = dict(np.load(path, allow_pickle=False))
    key = sorted(k for k in blob if k.startswith("param/"))[0]
    arr = np.array(blob[key])
    arr.flat[0] += 1.0
    blob[key] = arr
    with open(path, "wb") as f:
        np.savez(f, **blob)


def test_digest_mismatch_rejected_and_resume_falls_back(tmp_path):
    t = trained_trainer(tmp_path)
    mdir = str(tmp_path / "m")
    t.save_model(os.path.join(mdir, "0001.model.npz"))
    t.save_model(os.path.join(mdir, "0002.model.npz"))
    _corrupt_array(os.path.join(mdir, "0002.model.npz"))
    with pytest.raises(SnapshotIntegrityError, match="digest"):
        NetTrainer(parse_config(MLP_CONF)).load_model(
            os.path.join(mdir, "0002.model.npz"))
    mon = Monitor(MemorySink())
    rep = find_latest_valid(mdir, monitor=mon)
    assert rep.counter == 1
    assert rep.quarantined == ["0002.model.npz"]
    assert os.path.exists(
        os.path.join(mdir, "0002.model.npz.quarantined"))


# -- fault injection: ENOSPC / torn remote commit -------------------------


def test_enospc_mid_serialize_direct_api_raises(tmp_path, faultfs):
    t = trained_trainer(tmp_path)
    faultfs.enospc_after = 4096
    with pytest.raises(OSError, match="space"):
        t.save_model("fault://ckpt/0001.model.npz")
    assert faultfs.store == {}            # nothing half-committed


def test_enospc_managed_save_warns_and_training_survives(tmp_path,
                                                         faultfs,
                                                         capsys):
    """A full disk mid-snapshot must not kill a training run: the
    managed path downgrades the failure to a warning + telemetry."""
    conf = write_conf(tmp_path, model_dir="fault://ckpt",
                      extra="monitor = jsonl\nmonitor_path = %s\n"
                            % (tmp_path / "mon.jsonl"))
    faultfs.enospc_after = 4096
    assert main([conf]) == 0              # run completes
    assert not scan_snapshots("fault://ckpt")
    assert "checkpoint_write_failed" in capsys.readouterr().err
    recs = read_jsonl(str(tmp_path / "mon.jsonl"))
    validate_records(recs)
    cps = [r for r in recs if r["event"] == "checkpoint"]
    assert cps and all(r["status"] == "failed" for r in cps)
    assert all("space" in r["error"] for r in cps)


def test_remote_payload_without_manifest_is_uncommitted(tmp_path,
                                                        faultfs):
    """Remote torn commit: the writer died between the payload and the
    .ok manifest — resume must treat the payload as uncommitted."""
    t = trained_trainer(tmp_path)
    t.save_model("fault://ckpt/0001.model.npz")
    assert scan_snapshots("fault://ckpt") == [(1, "0001.model.npz")]
    faultfs.fail_write_substr = ".ok"
    with pytest.raises(IOError, match="injected write failure"):
        t.save_model("fault://ckpt/0002.model.npz")
    faultfs.clear_faults()
    assert "fault://ckpt/0002.model.npz" in faultfs.store  # payload..
    rep = find_latest_valid("fault://ckpt")   # ..but not committed
    assert rep.counter == 1


def test_remote_rewrite_drops_manifest_before_payload(tmp_path,
                                                      faultfs):
    """Re-committing an already-committed counter (emergency snapshots
    reuse the in-progress round's number): the old manifest must be
    gone BEFORE the payload is overwritten, so a kill mid-overwrite
    leaves an uncommitted payload — never a torn payload a stale
    manifest still vouches for."""
    t = trained_trainer(tmp_path)
    t.save_model("fault://rw/0001.model.npz")
    faultfs.fail_write_substr = "0001.model.npz"   # die at the payload
    with pytest.raises(IOError, match="injected write failure"):
        t.save_model("fault://rw/0001.model.npz")
    faultfs.clear_faults()
    # old payload bytes survive but the commit marker is gone:
    # uncommitted, not committed-but-torn
    assert "fault://rw/0001.model.npz" in faultfs.store
    assert "fault://rw/0001.model.npz.ok" not in faultfs.store
    assert scan_snapshots("fault://rw") == []


def test_scan_snapshots_is_read_only_for_inflight_tmp(tmp_path):
    """tools/ckpt_verify.py may be pointed at a model_dir a live run
    is committing into: scan_snapshots must never delete its in-flight
    .tmp (only the resume scan, which owns the dir, sweeps them)."""
    t = trained_trainer(tmp_path)
    mdir = str(tmp_path / "m")
    t.save_model(os.path.join(mdir, "0001.model.npz"))
    tmp = os.path.join(mdir, "0002.model.npz.tmp")
    with open(tmp, "wb") as f:
        f.write(b"in-flight")
    assert scan_snapshots(mdir) == [(1, "0001.model.npz")]
    assert os.path.exists(tmp)            # untouched by a bare scan
    import tools.ckpt_verify as cv
    assert cv.main([mdir, "--quiet"]) == 0
    assert os.path.exists(tmp)            # and by the offline verifier
    rep = find_latest_valid(mdir)         # resume DOES sweep it
    assert rep.counter == 1
    assert not os.path.exists(tmp)


def test_remote_torn_payload_detected_by_manifest(tmp_path, faultfs):
    """A torn write that still produced a commit manifest (buffered
    remote store ack'd short): manifest size check catches it and the
    resume scan quarantine-marks it."""
    t = trained_trainer(tmp_path)
    t.save_model("fault://ckpt/0001.model.npz")
    t.save_model("fault://ckpt/0002.model.npz")
    uri = "fault://ckpt/0002.model.npz"
    faultfs.store[uri] = faultfs.store[uri][:-2048]   # torn payload
    rep2 = verify_snapshot("fault://ckpt/0002.model.npz")
    assert not rep2["ok"] and "size mismatch" in rep2["error"]
    rep = find_latest_valid("fault://ckpt")
    assert rep.counter == 1
    assert rep.quarantined == ["0002.model.npz"]
    # the quarantine marker persists across scans
    assert "fault://ckpt/0002.model.npz.quarantined" in faultfs.store
    assert scan_snapshots("fault://ckpt") == [(1, "0001.model.npz")]


def test_continue_resumes_from_fake_remote_model_dir(tmp_path,
                                                     faultfs):
    """End-to-end over a registered remote scheme: train, corrupt the
    newest committed snapshot, continue=1 resumes from the survivor."""
    conf = write_conf(tmp_path, model_dir="fault://run")
    assert main([conf]) == 0
    assert [c for c, _ in scan_snapshots("fault://run")] == [2, 1]
    # corrupt the newest committed payload (manifest left matching in
    # size: flip bytes, not length — digest must catch it)
    uri = "fault://run/0002.model.npz"
    data = bytearray(faultfs.store[uri])
    data[len(data) // 2] ^= 0xFF
    faultfs.store[uri] = bytes(data)
    assert main([conf, "continue=1", "num_round=3"]) == 0
    # resumed from 0001 -> re-ran rounds 2 and 3 and committed both
    assert [c for c, _ in scan_snapshots("fault://run")] == [3, 2, 1]
    rep = verify_snapshot("fault://run/0002.model.npz")
    assert rep["ok"]                      # rewritten, valid again


# -- async writer ---------------------------------------------------------


def test_async_save_returns_before_commit(tmp_path):
    """The training thread pays only the gather: save() returns while
    the commit is still gated; close() drains it."""
    store = {}
    gate = threading.Event()

    class _GatedFile(io.BytesIO):
        def __init__(self, uri):
            super().__init__()
            self._uri = uri

        def close(self):
            gate.wait(timeout=30)
            store[self._uri] = self.getvalue()
            super().close()

    def _gated_open(uri, mode):
        f = _GatedFile(uri)
        return f if "b" in mode else io.TextIOWrapper(f)

    register_scheme("gated", _gated_open)
    try:
        t = trained_trainer(tmp_path)
        sink = MemorySink()
        ckpt = CheckpointManager(
            t, lambda c: "gated://m/%04d.model.npz" % c,
            model_dir="gated://m", monitor=Monitor(sink), async_=True)
        ckpt.save(1)
        assert store == {}                # commit still in flight
        gate.set()
        ckpt.close()
        assert "gated://m/0001.model.npz" in store
        recs = [r for r in sink.records if r["event"] == "checkpoint"]
        assert len(recs) == 1
        r = recs[0]
        assert r["status"] == "ok" and r["async_write"] is True
        assert r["gather_ms"] >= 0 and r["serialize_ms"] >= 0
        validate_records(sink.records)
    finally:
        register_scheme("gated", None)


def test_multi_rank_save_only_root_touches_file(tmp_path,
                                                monkeypatch):
    t = trained_trainer(tmp_path)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    path = str(tmp_path / "rank1" / "0001.model.npz")
    t.save_model(path)                    # non-root: gathers only
    assert not os.path.exists(os.path.dirname(path))
    ckpt = CheckpointManager(t, lambda c: path)
    ckpt.save(1)
    ckpt.close()
    assert not os.path.exists(os.path.dirname(path))


# -- retention ------------------------------------------------------------


def test_keep_snapshots_gc(tmp_path):
    conf = write_conf(tmp_path, extra="keep_snapshots = 2\n")
    assert main([conf, "num_round=5"]) == 0
    mdir = tmp_path / "models"
    assert sorted(os.listdir(mdir)) == ["0004.model.npz",
                                        "0005.model.npz"]


def test_retention_sweep_remote_removes_manifest_first(faultfs,
                                                       tmp_path):
    t = trained_trainer(tmp_path)
    for c in (1, 2, 3):
        t.save_model("fault://gc/%04d.model.npz" % c)
    removed = retention_sweep("fault://gc", keep=1)
    assert removed == ["0002.model.npz", "0001.model.npz"]
    assert set(faultfs.store) == {"fault://gc/0003.model.npz",
                                  "fault://gc/0003.model.npz.ok"}
    assert retention_sweep("fault://gc", keep=0) == []   # 0 = keep all


# -- preemption -----------------------------------------------------------


def test_sigterm_triggers_emergency_snapshot_and_resume(tmp_path,
                                                        monkeypatch,
                                                        capsys):
    """SIGTERM mid-round: emergency snapshot at the update boundary,
    schema-valid preempt telemetry, EXIT_PREEMPTED, and continue=1
    resumes from the emergency snapshot."""
    mon_path = str(tmp_path / "mon.jsonl")
    conf = write_conf(
        tmp_path,
        extra="dispatch_period = 1\nmonitor = jsonl\n"
              "monitor_path = %s\n" % mon_path)
    calls = {"n": 0}
    orig = NetTrainer.update

    def patched(self, batch):
        out = orig(self, batch)
        calls["n"] += 1
        if calls["n"] == 3:               # mid-round 0 (4 batches/rd)
            signal.raise_signal(signal.SIGTERM)
        return out

    monkeypatch.setattr(NetTrainer, "update", patched)
    rc = main([conf, "num_round=100000"])
    assert rc == EXIT_PREEMPTED
    monkeypatch.setattr(NetTrainer, "update", orig)
    mdir = tmp_path / "models"
    assert os.listdir(mdir) == ["0000.model.npz"]
    assert verify_snapshot(str(mdir / "0000.model.npz"))["ok"]
    recs = read_jsonl(mon_path)
    validate_records(recs)
    pre = [r for r in recs if r["event"] == "preempt"]
    assert len(pre) == 1
    assert pre[0]["signal"] == int(signal.SIGTERM)
    assert pre[0]["exit_code"] == EXIT_PREEMPTED
    cps = [r for r in recs if r["event"] == "checkpoint"]
    assert cps[-1]["emergency"] is True
    assert "preempted by signal" in capsys.readouterr().out
    # the run's SIGTERM handler was restored on exit
    assert signal.getsignal(signal.SIGTERM) in (
        signal.SIG_DFL, signal.default_int_handler)
    # and the emergency snapshot resumes: re-runs round 0 onward
    assert main([conf, "continue=1", "num_round=1"]) == 0
    assert "0001.model.npz" in os.listdir(mdir)


# -- stream retry ---------------------------------------------------------


def test_stream_retry_recovers_transient_open_failures(faultfs,
                                                       capsys):
    faultfs.store["fault://d/x.bin"] = b"payload"
    sink = MemorySink()
    set_global(Monitor(sink))
    faultfs.fail_opens = 2
    set_stream_retry(0)
    with pytest.raises(IOError):          # opt-in: off fails fast
        open_stream("fault://d/x.bin", "rb")
    faultfs.fail_opens = 2
    set_stream_retry(3, base_ms=1.0)
    with open_stream("fault://d/x.bin", "rb") as f:
        assert f.read() == b"payload"
    assert "stream_retry" in capsys.readouterr().err    # warn-once
    recs = [r for r in sink.records if r["event"] == "stream_retry"]
    assert recs and recs[0]["attempts"] == 2
    validate_records(sink.records)
    # exhausted retries still raise
    faultfs.fail_opens = 10
    with pytest.raises(IOError):
        open_stream("fault://d/x.bin", "rb")


def test_stream_retry_covers_snapshot_reads(faultfs, tmp_path):
    t = trained_trainer(tmp_path)
    t.save_model("fault://d/0001.model.npz")
    set_stream_retry(3, base_ms=1.0)
    faultfs.fail_reads = 2                # die mid-read, twice
    blob, meta = read_snapshot("fault://d/0001.model.npz")
    assert meta["content_digest"] == compute_digest(blob)


# -- offline verifier tool ------------------------------------------------


def test_ckpt_verify_tool(tmp_path, faultfs, capsys):
    import tools.ckpt_verify as cv
    t = trained_trainer(tmp_path)
    mdir = str(tmp_path / "m")
    t.save_model(os.path.join(mdir, "0001.model.npz"))
    t.save_model(os.path.join(mdir, "0002.model.npz"))
    assert cv.main([mdir]) == 0
    out = capsys.readouterr().out
    assert out.count("OK") == 2 and "0 corrupt" in out
    _corrupt_array(os.path.join(mdir, "0002.model.npz"))
    assert cv.main([mdir]) == 1
    assert "digest mismatch" in capsys.readouterr().out
    assert cv.main([os.path.join(mdir, "0001.model.npz")]) == 0
    capsys.readouterr()
    # remote: committed-good passes, manifest-less payload is reported
    # as uncommitted, not corruption
    t.save_model("fault://v/0001.model.npz")
    del faultfs.store["fault://v/0001.model.npz.ok"]
    t.save_model("fault://v/0002.model.npz")
    assert cv.main(["fault://v"]) == 0
    assert "UNCOMMITTED" in capsys.readouterr().out
    faultfs.truncate_tail = 512
    t.save_model("fault://v/0003.model.npz")
    faultfs.clear_faults()
    assert cv.main(["fault://v"]) == 1
    capsys.readouterr()
    # a missing/deleted remote snapshot URI is an unreadable FILE
    # (exit 1), never an empty dir's false all-clear
    assert cv.main(["fault://v/0099.model.npz"]) == 1
    assert "CORRUPT" in capsys.readouterr().out

"""``task = finetune`` (doc/tasks.md): remap-aware carry-over from a
verified snapshot or sealed bundle — typed shape-mismatch errors
naming the layer, layer-group LR scaling (``lr_mult`` / ``wmult`` /
``bmult``) with bit-identical frozen groups, resume preserving the
remap, and the end-to-end bundle -> remap -> train -> export -> boot
acceptance path with zero compile events on the matching-runtime
boot."""

import os

import numpy as np
import pytest

from cxxnet_tpu.main import main
from cxxnet_tpu.monitor import MemorySink, Monitor
from cxxnet_tpu.monitor.schema import read_jsonl, validate_records
from cxxnet_tpu.nnet.checkpoint import read_snapshot
from cxxnet_tpu.nnet.trainer import FinetuneShapeError, NetTrainer
from tests.test_main import write_conf
from tests.test_trainer import synth_idx


@pytest.fixture
def setup(tmp_path):
    """A trained 4-class source model + its sealed bundle + a 6-class
    finetune conf whose head (fc2) is remapped and whose backbone
    (fc1) carries a group multiplier."""
    pimg, plab = synth_idx(str(tmp_path), n=300, name="tr")
    pimg2, plab2 = synth_idx(str(tmp_path), n=100, seed=5, name="te")
    conf = write_conf(tmp_path, pimg, plab, pimg2, plab2,
                      extra="serve_buckets = 1,4\n"
                            "serve_max_batch = 4\n")
    assert main([conf, "num_round=1"]) == 0
    model = str(tmp_path / "models" / "0001.model.npz")
    assert main([conf, "task=export", "model_in=" + model]) == 0
    bundle = str(tmp_path / "models" / "0001.model.bundle")
    assert os.path.isdir(bundle)

    # 6-class head + per-group LR scaling on the carried backbone
    conf6 = (tmp_path / "run.conf").read_text() \
        .replace("layer[h->o] = fullc:fc2\n  nhidden = 4",
                 "layer[h->o] = fullc:fc2\n  nhidden = 6\n"
                 "  lr_mult = 4") \
        .replace("layer[+1:h] = fullc:fc1\n  nhidden = 32",
                 "layer[+1:h] = fullc:fc1\n  nhidden = 32\n"
                 "  wmult = 0.1\n  bmult = 0.1")
    p6 = str(tmp_path / "run6.conf")
    with open(p6, "w") as f:
        f.write(conf6)
    return tmp_path, conf, p6, model, bundle


def test_finetune_bundle_remap_end_to_end(setup):
    """The acceptance path: load the exported BUNDLE, remap the head
    to 6 classes, train with per-group LR scaling, export, boot the
    new bundle — carried weights digest-verified and bit-equal at the
    bootstrap, remapped head freshly sized, zero compile events on
    the matching-runtime boot."""
    tmp_path, conf, p6, model, bundle = setup
    mdir = str(tmp_path / "ft")
    mon_file = str(tmp_path / "ft.jsonl")
    assert main([p6, "task=finetune", "model_in=" + bundle,
                 "finetune_remap=fc2", "num_round=1",
                 "model_dir=" + mdir, "monitor=jsonl",
                 "monitor_path=" + mon_file]) == 0
    records = read_jsonl(mon_file)
    assert validate_records(records, strict=False) == []
    ft = [r for r in records if r["event"] == "finetune"]
    assert len(ft) == 1
    rec = ft[0]
    assert rec["source"] == bundle
    assert rec["carried_layers"] == ["fc1"]
    assert rec["remapped_layers"] == ["fc2"]
    assert rec["source_digest"].startswith("sha256:")
    # the source was loaded through its digest-verified read path:
    # the digest in the record is the source snapshot's sealed one
    _, src_meta = read_snapshot(model)
    assert rec["source_digest"] == src_meta["content_digest"]

    # remapped head is 6-wide; carried backbone left the source
    # bit-identical at the bootstrap (round 0 weights == source)
    snap, _ = read_snapshot(os.path.join(mdir, "0001.model.npz"))
    assert snap["param/fc2/wmat"].shape == (32, 6)
    assert snap["param/fc2/bias"].shape == (6,)

    # export the finetuned model and boot the new bundle: matching
    # runtime deserializes every program — zero compile events
    ft_model = os.path.join(mdir, "0001.model.npz")
    assert main([p6, "task=export", "model_in=" + ft_model]) == 0
    ft_bundle = os.path.join(mdir, "0001.model.bundle")
    from cxxnet_tpu.artifact.bundle import serve_cfg_from_bundle
    from cxxnet_tpu.serve import ServeSession
    sink = MemorySink()
    session = ServeSession(serve_cfg_from_bundle(ft_bundle),
                           model_path=ft_bundle,
                           monitor=Monitor(sink))
    try:
        out = session.predict(np.zeros((2, 256), np.float32))
        assert out.shape == (2, 6)       # the remapped head serves
    finally:
        session.close()
    compiles = [r for r in sink.records if r["event"] == "compile"]
    assert compiles == [], compiles
    art = [r for r in sink.records if r["event"] == "artifact_load"]
    assert len(art) == 1 and art[0]["fingerprint_match"]
    assert art[0]["hits"] > 0 and art[0]["rebuilds"] == 0


def test_shape_mismatch_without_remap_is_typed_and_names_layer(setup):
    """A changed layer NOT declared in finetune_remap raises
    FinetuneShapeError naming it; finetune_strict=0 restores the
    reference's silent skip-and-reinit."""
    tmp_path, conf, p6, model, bundle = setup
    with pytest.raises(FinetuneShapeError) as ei:
        main([p6, "task=finetune", "model_in=" + model,
              "num_round=1", "model_dir=" + str(tmp_path / "e")])
    assert ei.value.layer == "fc2"
    assert "fc2" in str(ei.value)
    assert "finetune_remap" in str(ei.value)
    # non-strict: the mismatched head silently re-inits (legacy)
    assert main([p6, "task=finetune", "model_in=" + model,
                 "finetune_strict=0", "num_round=1",
                 "model_dir=" + str(tmp_path / "ns")]) == 0
    snap, _ = read_snapshot(str(tmp_path / "ns" / "0001.model.npz"))
    assert snap["param/fc2/wmat"].shape == (32, 6)


def test_unknown_remap_layer_is_an_error(setup):
    tmp_path, conf, p6, model, bundle = setup
    with pytest.raises(ValueError, match="ghost"):
        main([p6, "task=finetune", "model_in=" + model,
              "finetune_remap=ghost", "num_round=1",
              "model_dir=" + str(tmp_path / "g")])


def test_frozen_group_is_bit_identical_after_updates(setup):
    """lr_mult = 0 freezes a layer group: after N real updates its
    weights are BIT-identical to the carried source (momentum starts
    at zero and the scheduled LR is exactly zero — not lr_minimum)."""
    tmp_path, conf, p6, model, bundle = setup
    frozen = (tmp_path / "run6.conf").read_text() \
        .replace("  wmult = 0.1\n  bmult = 0.1", "  lr_mult = 0")
    pf = str(tmp_path / "frozen.conf")
    with open(pf, "w") as f:
        f.write(frozen)
    mdir = str(tmp_path / "fr")
    assert main([pf, "task=finetune", "model_in=" + model,
                 "finetune_remap=fc2", "num_round=2",
                 "model_dir=" + mdir]) == 0
    src, _ = read_snapshot(model)
    out, _ = read_snapshot(os.path.join(mdir, "0002.model.npz"))
    # frozen backbone: bitwise unchanged across 2 rounds of updates
    np.testing.assert_array_equal(src["param/fc1/wmat"],
                                  out["param/fc1/wmat"])
    np.testing.assert_array_equal(src["param/fc1/bias"],
                                  out["param/fc1/bias"])
    # the remapped head DID train (lr_mult 4 on fc2)
    assert out["param/fc2/wmat"].shape == (32, 6)
    assert float(np.abs(out["param/fc2/wmat"]).sum()) > 0


def test_resume_preserves_remap(setup):
    """continue=1 on a finetune run resumes the run's OWN snapshot —
    the remapped head survives instead of being re-initialized from
    the original model_in. Proven bit-exactly: the resumed round runs
    with every group frozen, so 0002 must equal 0001 (a re-remap
    would have re-initialized fc2)."""
    tmp_path, conf, p6, model, bundle = setup
    frozen = (tmp_path / "run6.conf").read_text() \
        .replace("  wmult = 0.1\n  bmult = 0.1", "  lr_mult = 0") \
        .replace("  lr_mult = 4", "  lr_mult = 0")
    pf = str(tmp_path / "frozen_all.conf")
    with open(pf, "w") as f:
        f.write(frozen)
    mdir = str(tmp_path / "rs")
    assert main([pf, "task=finetune", "model_in=" + model,
                 "finetune_remap=fc2", "num_round=1",
                 "model_dir=" + mdir]) == 0
    assert main([pf, "task=finetune", "model_in=" + model,
                 "finetune_remap=fc2", "continue=1", "num_round=2",
                 "model_dir=" + mdir]) == 0
    a, _ = read_snapshot(os.path.join(mdir, "0001.model.npz"))
    b, _ = read_snapshot(os.path.join(mdir, "0002.model.npz"))
    assert b["param/fc2/wmat"].shape == (32, 6)
    # everything frozen: the resumed round must carry 0001's weights
    # forward bit-exactly — including the remapped head
    for k in ("param/fc1/wmat", "param/fc1/bias",
              "param/fc2/wmat", "param/fc2/bias"):
        np.testing.assert_array_equal(a[k], b[k], err_msg=k)


def test_lr_mult_and_aliases_scope_to_groups():
    """Unit surface: lr_mult composes with the schedule; wmult/bmult
    scope to their tags; tag-scoped wmat:lr_mult works; lr_mult=0
    beats the minimum-LR clamp."""
    from cxxnet_tpu.updater.param import UpdaterParam
    p = UpdaterParam(tag="wmat")
    p.set_param("lr", "0.5")
    p.set_param("lr_mult", "0.1")
    p.schedule_epoch(0)
    assert p.learning_rate == pytest.approx(0.05)

    p = UpdaterParam(tag="wmat")
    p.set_param("lr", "0.5")
    p.set_param("wmult", "2")
    p.set_param("bmult", "7")            # wrong tag: ignored
    p.schedule_epoch(0)
    assert p.learning_rate == pytest.approx(1.0)

    p = UpdaterParam(tag="bias")
    p.set_param("lr", "0.5")
    p.set_param("wmult", "2")            # wrong tag: ignored
    p.set_param("bmult", "3")
    p.schedule_epoch(0)
    assert p.learning_rate == pytest.approx(1.5)

    p = UpdaterParam(tag="bias")
    p.set_param("lr", "0.5")
    p.set_param("wmat:lr_mult", "9")     # other tag's scoped key
    p.set_param("bias:lr_mult", "0")
    p.schedule_epoch(0)
    assert p.learning_rate == 0.0        # exact zero, not lr_minimum


def test_trainer_finetune_from_plain_snapshot_matches_copy(tmp_path):
    """With no remap and identical structure, finetune_from carries
    exactly what copy_model_from carried (back-compat with the
    reference's name+shape matching)."""
    from cxxnet_tpu.utils.config import parse_config
    from tests.test_trainer import MLP_CONF
    src = NetTrainer(parse_config(MLP_CONF))
    src.init_model()
    path = str(tmp_path / "src.npz")
    src.save_model(path)

    a = NetTrainer(parse_config(MLP_CONF), mesh=src.mesh)
    a.init_model()
    rec = a.finetune_from(path)
    assert sorted(rec["carried_layers"]) == ["fc1", "fc2"]
    assert rec["remapped_layers"] == [] and rec["frozen_groups"] == []
    b = NetTrainer(parse_config(MLP_CONF), mesh=src.mesh)
    b.init_model()
    b.copy_model_from(path)
    for lk in ("fc1", "fc2"):
        for tag in ("wmat", "bias"):
            np.testing.assert_array_equal(
                np.asarray(a.params[lk][tag]),
                np.asarray(b.params[lk][tag]), err_msg=lk + tag)

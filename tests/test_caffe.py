"""Caffe .caffemodel import (tools/caffe.py) — counterpart of the
reference caffe converter (tools/caffe_converter/convert.cpp:29-187).

The fixture .caffemodel is hand-encoded protobuf wire format (the test
owns an independent encoder), covering both the V1 `layers=2` field and
the modern `layer=100` field, legacy 4-D blob shapes and BlobShape
dims, packed and unpacked float data.
"""

import struct

import numpy as np
import pytest

from cxxnet_tpu.tools.caffe import load_caffe
from cxxnet_tpu.tools.convert import convert


# ----------------------------------------------------- tiny pb encoder

def _varint(v: int) -> bytes:
    out = b""
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _ld(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def _blob_legacy(arr: np.ndarray, packed: bool = True) -> bytes:
    """BlobProto with legacy num/channels/height/width dims."""
    dims = list(arr.shape)
    dims = [1] * (4 - len(dims)) + dims
    msg = b"".join(_tag(i + 1, 0) + _varint(d)
                   for i, d in enumerate(dims))
    flat = np.asarray(arr, "<f4").ravel()
    if packed:
        msg += _ld(5, flat.tobytes())
    else:
        for v in flat:
            msg += _tag(5, 5) + struct.pack("<f", v)
    return msg


def _blob_shape(arr: np.ndarray) -> bytes:
    """BlobProto with BlobShape{dim}."""
    shape_msg = b"".join(_tag(1, 0) + _varint(d) for d in arr.shape)
    return _ld(7, shape_msg) + _ld(5, np.asarray(arr, "<f4")
                                   .ravel().tobytes())


def _v1_layer(name: str, blobs) -> bytes:
    msg = _ld(4, name.encode())
    for b in blobs:
        msg += _ld(6, b)
    return _ld(2, msg)                       # NetParameter.layers = 2


def _new_layer(name: str, blobs) -> bytes:
    msg = _ld(1, name.encode())
    for b in blobs:
        msg += _ld(7, b)
    return _ld(100, msg)                     # NetParameter.layer = 100


@pytest.fixture
def fixture_net(tmp_path):
    rng = np.random.RandomState(7)
    conv_w = rng.randn(8, 3, 3, 3).astype(np.float32)   # OIHW
    conv_b = rng.randn(8).astype(np.float32)
    fc_w = rng.randn(4, 32).astype(np.float32)          # (out, in)
    fc_b = rng.randn(4).astype(np.float32)
    net = (
        _v1_layer("data", []) +                          # no blobs: skip
        _v1_layer("conv1", [_blob_legacy(conv_w),
                            _blob_legacy(conv_b, packed=False)]) +
        _new_layer("fc1", [_blob_shape(fc_w), _blob_shape(fc_b)])
    )
    p = tmp_path / "model.caffemodel"
    p.write_bytes(net)
    return str(p), {"conv1.weight": conv_w, "conv1.bias": conv_b,
                    "fc1.weight": fc_w, "fc1.bias": fc_b}


def test_load_caffe(fixture_net):
    path, want = fixture_net
    got = load_caffe(path)
    assert set(got) == set(want)
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=1e-6)


def test_load_caffe_rejects_empty(tmp_path):
    p = tmp_path / "empty.caffemodel"
    p.write_bytes(_ld(1, b"netname"))
    with pytest.raises(ValueError, match="no parameterized layers"):
        load_caffe(str(p))


CONF = """
netconfig = start
layer[0->1] = conv:conv1
  kernel_size = 3
  nchannel = 8
layer[1->2] = relu
layer[2->3] = flatten
layer[3->4] = fullc:fc1
  nhidden = 4
layer[4->4] = softmax
netconfig = end
input_shape = 3,4,4
batch_size = 2
"""


def test_caffemodel_convert_forward_match(fixture_net, tmp_path):
    """Full converter path: .caffemodel -> model.npz whose forward
    matches a trainer with the same weights set by hand."""
    from cxxnet_tpu.io.data import DataBatch
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils.config import parse_config

    path, src = fixture_net
    # conv over 4x4 input -> 2x2x8 = 32 features into fc1 (4, 32)
    conf_path = tmp_path / "net.conf"
    conf_path.write_text(CONF)
    out_path = tmp_path / "out.model.npz"
    rc = convert(path, str(conf_path), str(out_path), silent=True)
    assert rc == 0

    t = NetTrainer(parse_config(CONF))
    t.load_model(str(out_path))
    # weights landed by name, in reference layout
    o, i, kh, kw = src["conv1.weight"].shape
    np.testing.assert_allclose(
        t.get_weight("conv1", "wmat"),
        src["conv1.weight"].reshape(o, i * kh * kw), rtol=1e-6)
    np.testing.assert_allclose(t.get_weight("fc1", "wmat"),
                               src["fc1.weight"], rtol=1e-6)

    # forward matches a hand-built equivalent
    t2 = NetTrainer(parse_config(CONF))
    t2.init_model()
    t2.set_weight("conv1", "wmat",
                  src["conv1.weight"].reshape(o, i * kh * kw))
    t2.set_weight("conv1", "bias", src["conv1.bias"])
    t2.set_weight("fc1", "wmat", src["fc1.weight"])
    t2.set_weight("fc1", "bias", src["fc1.bias"])
    rng = np.random.RandomState(0)
    batch = DataBatch(
        data=rng.rand(2, 4, 4, 3).astype(np.float32),
        label=np.zeros((2, 1), np.float32))
    p1 = t.predict(batch)
    f1 = t.extract_feature(batch, "top[-1]")
    f2 = t2.extract_feature(batch, "top[-1]")
    np.testing.assert_allclose(f1, f2, rtol=1e-5, atol=1e-6)
    assert p1.shape == (2,)


def test_convert_mean(tmp_path):
    """Caffe mean BlobProto -> augmenter .npy (convert_mean.cpp
    parity): CHW BGR becomes HWC RGB."""
    from cxxnet_tpu.tools.caffe import convert_mean

    rng = np.random.RandomState(3)
    mean_chw = rng.rand(3, 5, 6).astype(np.float32)   # BGR planes
    p = tmp_path / "mean.binaryproto"
    p.write_bytes(_blob_legacy(mean_chw[None]))       # (1, C, H, W)

    out_path = tmp_path / "mean.npy"
    got = convert_mean(str(p), str(out_path))
    assert got.shape == (5, 6, 3)
    # channel 0 of the output (R) is caffe channel 2
    np.testing.assert_allclose(got[:, :, 0], mean_chw[2], rtol=1e-6)
    np.testing.assert_allclose(got[:, :, 2], mean_chw[0], rtol=1e-6)
    np.testing.assert_allclose(np.load(out_path), got)

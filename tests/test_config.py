"""Config grammar + section routing tests, including parsing the actual
reference example configs (acceptance per SURVEY.md §7 step 1)."""

import os

import pytest

from cxxnet_tpu.utils.config import (ConfigError, parse_config,
                                     parse_cli_overrides, split_sections)

from tests.conftest import REFERENCE_DIR as REF, needs_reference


def test_basic_pairs():
    pairs = parse_config("a = 1\nb=2\n  c  =  hello\n")
    assert pairs == [("a", "1"), ("b", "2"), ("c", "hello")]


def test_comments_and_quotes():
    pairs = parse_config(
        '# leading comment\npath = "./data/my file" # trailing\nx=3\n')
    assert pairs == [("path", "./data/my file"), ("x", "3")]


def test_bracketed_keys():
    pairs = parse_config("metric[label] = error\nlayer[0->1] = fullc:fc1\n")
    assert pairs == [("metric[label]", "error"),
                     ("layer[0->1]", "fullc:fc1")]


def test_missing_value_raises():
    with pytest.raises(ConfigError):
        parse_config("a = ")
    with pytest.raises(ConfigError):
        parse_config("a b")


def test_cli_overrides():
    assert parse_cli_overrides(["max_round=3", "dev=tpu"]) == \
        [("max_round", "3"), ("dev", "tpu")]


@needs_reference
def test_split_sections_mnist():
    with open(os.path.join(REF, "example/MNIST/MNIST.conf")) as f:
        pairs = parse_config(f.read())
    blocks, glob = split_sections(pairs)
    assert len(blocks) == 2
    assert blocks[0]["kind"] == "data" and blocks[0]["name"] == "train"
    assert blocks[1]["kind"] == "eval" and blocks[1]["name"] == "test"
    assert ("iter", "mnist") in blocks[0]["cfg"]
    assert ("shuffle", "1") in blocks[0]["cfg"]
    # netconfig and learning params are global
    gk = [k for k, _ in glob]
    assert "netconfig" in gk and "eta" in gk and "batch_size" in gk
    # iterator params must NOT leak into globals
    assert "path_img" not in gk


@needs_reference
def test_split_sections_imagenet():
    with open(os.path.join(REF, "example/ImageNet/Inception-BN.conf")) as f:
        pairs = parse_config(f.read())
    blocks, glob = split_sections(pairs)
    assert len(blocks) >= 2
    kinds = [b["kind"] for b in blocks]
    assert "data" in kinds and "eval" in kinds


def test_cli_error_paths(tmp_path, capsys):
    """Misconfigurations fail fast with readable errors, not stack
    traces deep in the stack (reference utils::Check style)."""
    from cxxnet_tpu.main import LearnTask
    from cxxnet_tpu.io import create_iterator

    # unknown iterator type
    with pytest.raises(ValueError, match="unknown iterator type"):
        create_iterator([("iter", "nosuch")], [("batch_size", "4")])

    # adapter without a base iterator
    with pytest.raises(AssertionError):
        create_iterator([("iter", "threadbuffer")],
                        [("batch_size", "4")])

    # unterminated iterator block
    conf = tmp_path / "bad.conf"
    conf.write_text("data = train\niter = csv\n  filename = x.csv\n")
    with pytest.raises(ConfigError, match="not closed"):
        LearnTask().run([str(conf)])

    # unknown layer type surfaces by name (at net build)
    from cxxnet_tpu.graph import NetGraph
    from cxxnet_tpu.nnet.net import FuncNet
    g = NetGraph()
    g.configure(parse_config(
        "netconfig = start\nlayer[0->1] = nosuchlayer\n"
        "netconfig = end\ninput_shape = 1,1,4\nbatch_size = 2\n"))
    with pytest.raises(ValueError, match="nosuchlayer"):
        FuncNet(g, 2)

    # no config file -> usage print + rc 1, not a traceback
    assert LearnTask().run([]) == 1
    assert "Usage:" in capsys.readouterr().out

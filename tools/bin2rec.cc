/*!
 * \file bin2rec.cc
 * \brief convert a legacy BinaryPage archive (+ its image list, which
 *  holds the indices/labels the bin format does not store) into a
 *  RecordIO archive.
 *
 * Parity with /root/reference/tools/bin2rec.cc:25-71.
 * Usage: bin2rec img_list bin_file rec_file [label_width=1]
 * (extra label columns beyond the first are skipped, as in the
 *  reference)
 */
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <sstream>
#include <string>
#include <vector>

#include "../src/io/binpage.h"
#include "../src/io/recordio.h"

struct ImageRecHeader {
  uint32_t flag;
  float label;
  uint64_t image_id[2];
};

int main(int argc, char *argv[]) {
  if (argc < 4) {
    std::fprintf(stderr,
                 "Usage: bin2rec img_list bin_file rec_file "
                 "[label_width=1]\n");
    return 1;
  }
  int label_width = argc > 4 ? std::atoi(argv[4]) : 1;
  std::ifstream lst(argv[1]);
  if (!lst.good()) {
    std::fprintf(stderr, "cannot open %s\n", argv[1]);
    return 1;
  }
  std::FILE *fi = std::fopen(argv[2], "rb");
  if (fi == nullptr) {
    std::fprintf(stderr, "cannot open %s\n", argv[2]);
    return 1;
  }
  cxxnet_tpu::RecordIOWriter writer(argv[3]);
  if (!writer.is_open()) {
    std::fprintf(stderr, "cannot create %s\n", argv[3]);
    return 1;
  }
  cxxnet_tpu::BinaryPage page;
  std::string line;
  size_t imcnt = 0;
  std::vector<char> blob;
  while (page.Load(fi)) {
    for (int i = 0; i < page.Size(); ++i) {
      if (!std::getline(lst, line)) {
        std::fprintf(stderr, "bin2rec: image list shorter than bin\n");
        return 1;
      }
      std::istringstream is(line);
      ImageRecHeader hdr;
      std::memset(&hdr, 0, sizeof(hdr));
      double index = 0;
      float label = 0;
      if (!(is >> index >> label)) {
        std::fprintf(stderr, "bin2rec: bad list row: %s\n", line.c_str());
        return 1;
      }
      for (int k = 1; k < label_width; ++k) {
        float skip;
        is >> skip;
      }
      hdr.image_id[0] = static_cast<uint64_t>(index);
      hdr.label = label;
      size_t sz = 0;
      const void *dptr = page.Get(i, &sz);
      blob.resize(sizeof(hdr) + sz);
      std::memcpy(blob.data(), &hdr, sizeof(hdr));
      std::memcpy(blob.data() + sizeof(hdr), dptr, sz);
      writer.WriteRecord(blob.data(), blob.size());
      ++imcnt;
    }
  }
  std::fclose(fi);
  writer.Close();
  if (writer.HasError()) {
    std::fprintf(stderr, "bin2rec: write failed (disk full?)\n");
    return 1;
  }
  std::printf("bin2rec: converted %zu images\n", imcnt);
  return 0;
}

/*!
 * \file im2rec.cc
 * \brief pack images into a RecordIO archive.
 *
 * Parity with /root/reference/tools/im2rec.cc:24-139: reads an image
 * list ("index label... path" rows), optionally resizes the short edge
 * and re-encodes JPEG via OpenCV, writes image records (24-byte header
 * + jpeg bytes) into <out>.rec; nsplit/part shard the list for
 * parallel packing. label_width=N packs ALL N list labels into the
 * record (header flag 'ML'|N + N-1 extra f32 after the header — the
 * reference only validates the extra labels, tools/im2rec.cc:83-87;
 * here the archive carries them, see cxxnet_tpu/io/recordio.py).
 *
 * Usage: im2rec <image.lst> <image_root> <output.rec>
 *               [resize=0] [quality=95] [nsplit=1] [part=0]
 *               [label_width=1]
 */
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <sstream>
#include <string>
#include <vector>

#include <opencv2/opencv.hpp>

#include "../src/io/recordio.h"

struct ImageRecHeader {
  uint32_t flag;
  float label;
  uint64_t image_id[2];
};

int main(int argc, char *argv[]) {
  if (argc < 4) {
    std::fprintf(stderr,
                 "Usage: im2rec image.lst image_root output.rec "
                 "[resize=0] [quality=95] [nsplit=1] [part=0] "
                 "[label_width=1]\n");
    return 1;
  }
  int resize = 0, quality = 95, nsplit = 1, part = 0, label_width = 1;
  for (int i = 4; i < argc; ++i) {
    char key[64];
    int val;
    if (std::sscanf(argv[i], "%63[^=]=%d", key, &val) == 2) {
      if (!std::strcmp(key, "resize")) resize = val;
      if (!std::strcmp(key, "quality")) quality = val;
      if (!std::strcmp(key, "nsplit")) nsplit = val;
      if (!std::strcmp(key, "part")) part = val;
      if (!std::strcmp(key, "label_width")) label_width = val;
    }
  }
  if (label_width < 1 || label_width > 0xFFFF) {
    std::fprintf(stderr, "label_width out of range: %d\n", label_width);
    return 1;
  }
  std::ifstream lst(argv[1]);
  if (!lst.good()) {
    std::fprintf(stderr, "cannot open %s\n", argv[1]);
    return 1;
  }
  std::string root = argv[2];
  if (!root.empty() && root.back() != '/') root += '/';
  std::string outpath = argv[3];
  if (nsplit > 1) {
    char buf[32];
    std::snprintf(buf, sizeof(buf), ".part%d", part);
    outpath += buf;
  }
  cxxnet_tpu::RecordIOWriter writer(outpath.c_str());
  if (!writer.is_open()) {
    std::fprintf(stderr, "cannot create %s\n", outpath.c_str());
    return 1;
  }

  std::string line;
  size_t count = 0, lineno = 0, myrows = 0;
  std::string blob;
  std::vector<uint8_t> encoded;
  while (std::getline(lst, line)) {
    size_t myline = lineno++;
    if (nsplit > 1 &&
        static_cast<int>(myline % static_cast<size_t>(nsplit)) != part) {
      continue;
    }
    ++myrows;
    std::istringstream is(line);
    double index, label;
    std::string path;
    if (line.find_first_not_of(" \t\r\n") == std::string::npos) {
      continue;                           /* blank line */
    }
    if (!(is >> index >> label)) {
      std::fprintf(stderr, "unparseable list row: %s\n", line.c_str());
      return 1;
    }
    std::vector<float> extra_labels;
    for (int k = 1; k < label_width; ++k) {
      double tmp;
      if (!(is >> tmp)) {
        std::fprintf(stderr,
                     "invalid list row (label_width=%d?): %s\n",
                     label_width, line.c_str());
        return 1;
      }
      extra_labels.push_back(static_cast<float>(tmp));
    }
    // the path is the REST of the line (paths may contain spaces —
    // same bounded-split rule as the Python imglist parser), trimmed
    // of surrounding whitespace and any \r from CRLF lists
    std::getline(is, path);
    std::string::size_type b = path.find_first_not_of(" \t\r\n");
    if (b == std::string::npos) {
      std::fprintf(stderr, "list row missing image path: %s\n",
                   line.c_str());
      return 1;
    }
    path = path.substr(b, path.find_last_not_of(" \t\r\n") - b + 1);
    // a purely numeric FIRST path token with more tokens after it
    // means the list likely has MORE labels than label_width — a
    // silent misparse (the "path" would fail to open and each row be
    // skipped, the tool exiting 0 with an empty archive). Spaced paths
    // whose first token is non-numeric pack fine.
    std::istringstream ps(path);
    std::string tok0, trailing;
    ps >> tok0;
    char *endp = nullptr;
    std::strtod(tok0.c_str(), &endp);
    if (endp != nullptr && *endp == '\0' && (ps >> trailing)) {
      // ambiguous row: could be excess labels OR a legitimate spaced
      // path whose first component is numeric ("2012 photos/img.jpg").
      // If the assembled path exists on disk it is clearly the latter
      // — warn and pack it; only hard-reject when it does not resolve.
      std::string probe = root + path;
      std::ifstream exists(probe.c_str(), std::ios::binary);
      if (exists.good()) {
        std::fprintf(stderr,
                     "warning: path %s starts with a numeric token but "
                     "exists on disk — packing it as a spaced path\n",
                     path.c_str());
      } else {
        std::fprintf(stderr,
                     "numeric path token %s followed by %s — does the "
                     "list have more labels than label_width=%d? (if "
                     "this is a spaced path whose first directory is "
                     "numeric, the file %s was not found under the "
                     "image root)\n",
                     tok0.c_str(), trailing.c_str(), label_width,
                     probe.c_str());
        return 1;
      }
    }
    std::string full = root + path;

    ImageRecHeader hdr;
    std::memset(&hdr, 0, sizeof(hdr));
    hdr.label = static_cast<float>(label);
    hdr.image_id[0] = static_cast<uint64_t>(index);
    if (label_width > 1) {
      hdr.flag = 0x4D4C0000u |                  /* 'ML' tag */
                 static_cast<uint32_t>(label_width);
    }

    const uint8_t *payload = nullptr;
    size_t payload_size = 0;
    std::vector<uint8_t> filebuf;
    if (resize == 0) {
      // pack raw bytes, no decode round-trip
      FILE *fi = std::fopen(full.c_str(), "rb");
      if (fi == nullptr) {
        std::fprintf(stderr, "skip unreadable %s\n", full.c_str());
        continue;
      }
      std::fseek(fi, 0, SEEK_END);
      long sz = std::ftell(fi);
      std::fseek(fi, 0, SEEK_SET);
      filebuf.resize(static_cast<size_t>(sz));
      if (std::fread(filebuf.data(), 1, filebuf.size(), fi) !=
          filebuf.size()) {
        std::fclose(fi);
        continue;
      }
      std::fclose(fi);
      payload = filebuf.data();
      payload_size = filebuf.size();
    } else {
      cv::Mat img = cv::imread(full, cv::IMREAD_COLOR);
      if (img.empty()) {
        std::fprintf(stderr, "skip undecodable %s\n", full.c_str());
        continue;
      }
      // resize short edge (tools/im2rec.cc parity)
      int h = img.rows, w = img.cols;
      cv::Mat resized;
      if (h < w) {
        cv::resize(img, resized,
                   cv::Size(w * resize / h, resize));
      } else {
        cv::resize(img, resized,
                   cv::Size(resize, h * resize / w));
      }
      std::vector<int> params = {cv::IMWRITE_JPEG_QUALITY, quality};
      cv::imencode(".jpg", resized, encoded, params);
      payload = encoded.data();
      payload_size = encoded.size();
    }
    size_t extra_bytes = extra_labels.size() * sizeof(float);
    blob.resize(sizeof(hdr) + extra_bytes + payload_size);
    std::memcpy(&blob[0], &hdr, sizeof(hdr));
    if (extra_bytes > 0) {
      std::memcpy(&blob[sizeof(hdr)], extra_labels.data(), extra_bytes);
    }
    std::memcpy(&blob[sizeof(hdr) + extra_bytes], payload, payload_size);
    writer.WriteRecord(blob.data(), blob.size());
    if (++count % 1000 == 0) {
      std::printf("%zu images packed\n", count);
    }
  }
  writer.Close();
  if (writer.HasError()) {
    std::fprintf(stderr, "im2rec: write failed (disk full?): %s\n",
                 outpath.c_str());
    return 1;
  }
  if (count == 0 && myrows > 0) {
    std::fprintf(stderr, "im2rec: no images packed from %zu list rows\n",
                 myrows);
    return 1;
  }
  std::printf("im2rec: packed %zu images into %s\n", count,
              outpath.c_str());
  return 0;
}

/*!
 * \file im2rec.cc
 * \brief pack images into a RecordIO archive.
 *
 * Parity with /root/reference/tools/im2rec.cc:24-139: reads an image
 * list ("index label path" rows), optionally resizes the short edge and
 * re-encodes JPEG via OpenCV, writes image records (24-byte header +
 * jpeg bytes) into <out>.rec; nsplit/part shard the list for parallel
 * packing.
 *
 * Usage: im2rec <image.lst> <image_root> <output.rec>
 *               [resize=0] [quality=95] [nsplit=1] [part=0]
 */
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <sstream>
#include <string>
#include <vector>

#include <opencv2/opencv.hpp>

#include "../src/io/recordio.h"

struct ImageRecHeader {
  uint32_t flag;
  float label;
  uint64_t image_id[2];
};

int main(int argc, char *argv[]) {
  if (argc < 4) {
    std::fprintf(stderr,
                 "Usage: im2rec image.lst image_root output.rec "
                 "[resize=0] [quality=95] [nsplit=1] [part=0]\n");
    return 1;
  }
  int resize = 0, quality = 95, nsplit = 1, part = 0;
  for (int i = 4; i < argc; ++i) {
    char key[64];
    int val;
    if (std::sscanf(argv[i], "%63[^=]=%d", key, &val) == 2) {
      if (!std::strcmp(key, "resize")) resize = val;
      if (!std::strcmp(key, "quality")) quality = val;
      if (!std::strcmp(key, "nsplit")) nsplit = val;
      if (!std::strcmp(key, "part")) part = val;
    }
  }
  std::ifstream lst(argv[1]);
  if (!lst.good()) {
    std::fprintf(stderr, "cannot open %s\n", argv[1]);
    return 1;
  }
  std::string root = argv[2];
  if (!root.empty() && root.back() != '/') root += '/';
  std::string outpath = argv[3];
  if (nsplit > 1) {
    char buf[32];
    std::snprintf(buf, sizeof(buf), ".part%d", part);
    outpath += buf;
  }
  cxxnet_tpu::RecordIOWriter writer(outpath.c_str());
  if (!writer.is_open()) {
    std::fprintf(stderr, "cannot create %s\n", outpath.c_str());
    return 1;
  }

  std::string line;
  size_t count = 0, lineno = 0;
  std::string blob;
  std::vector<uint8_t> encoded;
  while (std::getline(lst, line)) {
    size_t myline = lineno++;
    if (nsplit > 1 &&
        static_cast<int>(myline % static_cast<size_t>(nsplit)) != part) {
      continue;
    }
    std::istringstream is(line);
    double index, label;
    std::string path;
    if (!(is >> index >> label >> path)) continue;
    std::string full = root + path;

    ImageRecHeader hdr;
    std::memset(&hdr, 0, sizeof(hdr));
    hdr.label = static_cast<float>(label);
    hdr.image_id[0] = static_cast<uint64_t>(index);

    const uint8_t *payload = nullptr;
    size_t payload_size = 0;
    std::vector<uint8_t> filebuf;
    if (resize == 0) {
      // pack raw bytes, no decode round-trip
      FILE *fi = std::fopen(full.c_str(), "rb");
      if (fi == nullptr) {
        std::fprintf(stderr, "skip unreadable %s\n", full.c_str());
        continue;
      }
      std::fseek(fi, 0, SEEK_END);
      long sz = std::ftell(fi);
      std::fseek(fi, 0, SEEK_SET);
      filebuf.resize(static_cast<size_t>(sz));
      if (std::fread(filebuf.data(), 1, filebuf.size(), fi) !=
          filebuf.size()) {
        std::fclose(fi);
        continue;
      }
      std::fclose(fi);
      payload = filebuf.data();
      payload_size = filebuf.size();
    } else {
      cv::Mat img = cv::imread(full, cv::IMREAD_COLOR);
      if (img.empty()) {
        std::fprintf(stderr, "skip undecodable %s\n", full.c_str());
        continue;
      }
      // resize short edge (tools/im2rec.cc parity)
      int h = img.rows, w = img.cols;
      cv::Mat resized;
      if (h < w) {
        cv::resize(img, resized,
                   cv::Size(w * resize / h, resize));
      } else {
        cv::resize(img, resized,
                   cv::Size(resize, h * resize / w));
      }
      std::vector<int> params = {cv::IMWRITE_JPEG_QUALITY, quality};
      cv::imencode(".jpg", resized, encoded, params);
      payload = encoded.data();
      payload_size = encoded.size();
    }
    blob.resize(sizeof(hdr) + payload_size);
    std::memcpy(&blob[0], &hdr, sizeof(hdr));
    std::memcpy(&blob[sizeof(hdr)], payload, payload_size);
    writer.WriteRecord(blob.data(), blob.size());
    if (++count % 1000 == 0) {
      std::printf("%zu images packed\n", count);
    }
  }
  writer.Close();
  if (writer.HasError()) {
    std::fprintf(stderr, "im2rec: write failed (disk full?): %s\n",
                 outpath.c_str());
    return 1;
  }
  std::printf("im2rec: packed %zu images into %s\n", count,
              outpath.c_str());
  return 0;
}

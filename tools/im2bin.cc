/*!
 * \file im2bin.cc
 * \brief pack images (raw file bytes, no decode) into a BinaryPage
 *  archive — the legacy imgbin format.
 *
 * Parity with /root/reference/tools/im2bin.cpp:7-68: reads an image
 * list ("index label path" rows), appends each file's bytes to the
 * current page, flushing full pages.
 *
 * Usage: im2bin image.lst image_root output.bin
 */
#include <cstdio>
#include <cstdlib>
#include <fstream>
#include <sstream>
#include <string>
#include <vector>

#include "../src/io/binpage.h"

int main(int argc, char *argv[]) {
  if (argc != 4) {
    std::fprintf(stderr, "Usage: im2bin image.lst image_root output.bin\n");
    return 1;
  }
  std::ifstream lst(argv[1]);
  if (!lst.good()) {
    std::fprintf(stderr, "cannot open %s\n", argv[1]);
    return 1;
  }
  std::string root = argv[2];
  if (!root.empty() && root.back() != '/') root += '/';
  std::FILE *out = std::fopen(argv[3], "wb");
  if (out == nullptr) {
    std::fprintf(stderr, "cannot create %s\n", argv[3]);
    return 1;
  }
  cxxnet_tpu::BinaryPage page;
  size_t imcnt = 0, pgcnt = 0;
  std::string line;
  bool write_ok = true;
  while (std::getline(lst, line)) {
    if (line.empty()) continue;
    std::istringstream is(line);
    double index, label;
    std::string path;
    if (!(is >> index >> label)) continue;
    // rest of line is the path — may contain spaces (reference parses
    // with fscanf "%[^\n]", im2bin.cpp:29)
    std::getline(is, path);
    size_t b = path.find_first_not_of(" \t");
    if (b == std::string::npos) continue;
    path = path.substr(b);
    std::ifstream img(root + path, std::ios::binary);
    if (!img.good()) {
      std::fprintf(stderr, "im2bin: cannot open image %s\n",
                   (root + path).c_str());
      return 1;
    }
    std::vector<char> bytes((std::istreambuf_iterator<char>(img)),
                            std::istreambuf_iterator<char>());
    if (bytes.size() + 16 > cxxnet_tpu::BinaryPage::kPageBytes) {
      std::fprintf(stderr, "im2bin: image %s too large for one page\n",
                   path.c_str());
      return 1;
    }
    if (!page.Push(bytes.data(), bytes.size())) {
      write_ok = write_ok && page.Save(out);
      page.Clear();
      ++pgcnt;
      if (!page.Push(bytes.data(), bytes.size())) {
        std::fprintf(stderr, "im2bin: image %s too large\n", path.c_str());
        return 1;
      }
    }
    ++imcnt;
  }
  if (page.Size() != 0) {
    write_ok = write_ok && page.Save(out);
    ++pgcnt;
  }
  if (std::fclose(out) != 0) write_ok = false;
  if (!write_ok) {
    std::fprintf(stderr, "im2bin: write failed (disk full?)\n");
    return 1;
  }
  std::printf("im2bin: packed %zu images into %zu pages\n", imcnt, pgcnt);
  return 0;
}

#!/usr/bin/env python
"""Offline snapshot/artifact integrity checker — the operator's first
debugging step when a resume or artifact boot misbehaves
(doc/checkpointing.md, doc/artifacts.md).

For each argument (a snapshot file, a sealed artifact bundle, or a
model_dir to scan — local path or remote URI, anything the stream
layer opens) it reports structural loadability, the content digest
verdict, the format version, and (remote) the commit-manifest
cross-check. Bundles additionally verify every member's sha256 (the
serialized executables included) and the snapshot inside::

    python tools/ckpt_verify.py ./models
    python tools/ckpt_verify.py gs://bucket/run7/0042.model.npz
    python tools/ckpt_verify.py ./models/0042.model.bundle

Exit status: 0 = every checked artifact verifies; 1 = at least one is
corrupt, truncated, digest-mismatched, or format-incompatible (an
empty model_dir is not corruption); 2 = usage error. The fault-matrix
tests drive this binary against injected ENOSPC/truncation/torn-commit
states, so its verdicts are pinned behavior, not best-effort output.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from cxxnet_tpu.artifact.bundle import (BUNDLE_RE, is_bundle,
                                        scan_bundles, verify_bundle)
from cxxnet_tpu.nnet.checkpoint import (MODEL_RE, scan_snapshots,
                                        snapshot_uri, verify_snapshot)
from cxxnet_tpu.utils.stream import (list_stream_dir, stream_exists,
                                     uri_scheme)


def _bundle_target(target: str) -> bool:
    """A target to verify as a bundle: any directory holding a
    manifest, or anything NAMED like a bundle — a vanished/tampered
    manifest on a ``NNNN.model.bundle`` path must report CORRUPT
    (exit 1), never fall through to an empty-dir all-clear."""
    if is_bundle(target):
        return True
    return bool(BUNDLE_RE.match(target.rstrip("/").rsplit("/", 1)[-1]))


def _is_dir(target: str) -> bool:
    if uri_scheme(target):
        # object stores have no real dirs: a URI whose basename looks
        # like a snapshot is ALWAYS checked as a file — a missing one
        # must report CORRUPT/unreadable (exit 1), never read as an
        # empty dir (exit 0, a false all-clear on a vanished
        # snapshot). Anything else is a dir unless it opens.
        if MODEL_RE.match(target.rsplit("/", 1)[-1]):
            return False
        return not stream_exists(target)
    return os.path.isdir(target)


def _check(path: str, quiet: bool) -> bool:
    rep = verify_snapshot(path)
    if rep["ok"]:
        if not quiet:
            print("OK       %s  (%d bytes, format v%d, digest %s)"
                  % (path, rep["bytes"], rep["format_version"],
                     rep["digest"]))
        return True
    print("CORRUPT  %s  (%s)" % (path, rep["error"]))
    return False


def _check_bundle(path: str, quiet: bool) -> bool:
    """Verify a sealed artifact bundle: commit marker, manifest sha,
    every member digest (executables included), and the snapshot
    inside — a tampered byte anywhere fails the whole bundle."""
    rep = verify_bundle(path)
    if rep["ok"]:
        if not quiet:
            print("OK       %s  (bundle, format v%d, %d members, "
                  "%d programs)"
                  % (path, rep["format_version"], rep["members"],
                     rep["programs"]))
        return True
    print("CORRUPT  %s  (bundle: %s)" % (path, rep["error"]))
    return False


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ckpt_verify",
        description="verify snapshot integrity (digest + structural "
                    "loadability), local or remote")
    ap.add_argument("targets", nargs="+",
                    help="snapshot files and/or model_dir paths/URIs")
    ap.add_argument("--quiet", action="store_true",
                    help="print corrupt snapshots only")
    args = ap.parse_args(argv)

    checked = 0
    bad = 0
    for target in args.targets:
        if _bundle_target(target):
            # an explicitly named bundle must verify commit marker
            # and all: an uncommitted one is a failure here (naming
            # it means you expect it deployable), unlike the
            # skip-and-report treatment inside a dir scan
            checked += 1
            if not _check_bundle(target, args.quiet):
                bad += 1
        elif _is_dir(target):
            names = [n for _, n in scan_snapshots(target)]
            bundles = [n for _, n in scan_bundles(target)]
            # uncommitted remote payloads (no .ok) are *reported* but
            # not counted as corruption: resume ignores them by design
            listing = set(list_stream_dir(target))
            if uri_scheme(target):
                for n in sorted(listing):
                    if MODEL_RE.match(n) and n + ".ok" not in listing:
                        print("UNCOMMITTED %s  (payload without "
                              "commit manifest; resume ignores it)"
                              % snapshot_uri(target, n))
            # uncommitted bundles likewise: the exporter may still be
            # writing them, and the hot-swap watcher skips them
            for n in sorted(listing):
                if BUNDLE_RE.match(n) and n not in bundles:
                    print("UNCOMMITTED %s  (bundle without commit "
                          "marker; the watcher ignores it)"
                          % snapshot_uri(target, n))
            if not names and not bundles and not args.quiet:
                print("EMPTY    %s  (no committed snapshots or "
                      "bundles)" % target)
            for n in names:
                checked += 1
                if not _check(snapshot_uri(target, n), args.quiet):
                    bad += 1
            for n in bundles:
                checked += 1
                if not _check_bundle(snapshot_uri(target, n),
                                     args.quiet):
                    bad += 1
        else:
            checked += 1
            if not _check(target, args.quiet):
                bad += 1
    if not args.quiet:
        print("checked %d snapshot(s), %d corrupt" % (checked, bad))
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())

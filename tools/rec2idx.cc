/*!
 * \file rec2idx.cc
 * \brief inspect / index a RecordIO archive: prints one line per record
 *  (image_id, label, payload bytes) — a debugging companion to im2rec
 *  (stands in for the reference's bin2rec-era tooling on .rec files).
 */
#include <cstdio>
#include <cstring>
#include <string>

#include "../src/io/recordio.h"

struct ImageRecHeader {
  uint32_t flag;
  float label;
  uint64_t image_id[2];
};

int main(int argc, char *argv[]) {
  if (argc < 2) {
    std::fprintf(stderr,
                 "Usage: rec2idx archive.rec [part] [nparts]\n");
    return 1;
  }
  int part = argc > 2 ? std::atoi(argv[2]) : 0;
  int nparts = argc > 3 ? std::atoi(argv[3]) : 1;
  cxxnet_tpu::RecordIOReader reader(argv[1], part, nparts);
  if (!reader.is_open()) {
    std::fprintf(stderr, "cannot open %s\n", argv[1]);
    return 1;
  }
  std::string rec;
  size_t n = 0;
  while (reader.NextRecord(&rec)) {
    if (rec.size() >= sizeof(ImageRecHeader)) {
      ImageRecHeader hdr;
      std::memcpy(&hdr, rec.data(), sizeof(hdr));
      std::printf("%llu\t%g\t%zu\n",
                  static_cast<unsigned long long>(hdr.image_id[0]),
                  hdr.label, rec.size() - sizeof(hdr));
    }
    ++n;
  }
  std::fprintf(stderr, "%zu records\n", n);
  return 0;
}

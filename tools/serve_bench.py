#!/usr/bin/env python
"""Closed-loop serve benchmark: client sweep over the serve subsystem.

Drives N threaded closed-loop clients (each waits for its result before
sending the next request) through a ``ServeSession`` and reports one
BENCH-style JSON record on stdout: per-sweep-point request throughput,
latency p50/p99, micro-batch fill rate and pad fraction — all read back
from the schema-validated ``serve_*`` telemetry records rather than
re-derived timers (the bench.py rule), plus a ``zero_recompiles``
verdict (no XLA compile events after warmup at any sweep point).

Default is a self-contained synthetic MLP on whatever platform jax
picks (set ``JAX_PLATFORMS=cpu`` for the CPU smoke run); pass
``--conf``/``--model-in`` to sweep a real snapshot instead.

``--tenants`` switches to the closed-loop **multi-tenant fleet
scenario** (ROADMAP item 2): per-tenant client mixes with token-bucket
quotas driven through the real binary-protocol front end
(``serve/frontend.py``) — per-tenant ok/shed counts, shed rate, and
latency p50/p99 read back from the ``serve_http`` records, plus a
p99-SLO assertion: ``--slo-p99-ms`` makes the process exit 3 (distinct
from 1 = post-warmup recompiles; argparse owns 2 — the ``bench.py``
exit-code convention) when any tenant's ok-request p99 breaches the
SLO. The point of quota shedding is that *surviving* requests stay
fast — the SLO applies to every tenant's completed requests, shed or
not.

Usage::

    JAX_PLATFORMS=cpu python tools/serve_bench.py --clients 1,2,4,8
    python tools/serve_bench.py --conf run.conf --model-in 0010.model.npz
    JAX_PLATFORMS=cpu python tools/serve_bench.py \
        --tenants gold:4,free:4:50:8 --slo-p99-ms 250
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SYNTH_CONF = """
netconfig=start
layer[+1:h] = fullc:fc1
  nhidden = 64
  init_sigma = 0.05
layer[+1] = relu
layer[h->o] = fullc:fc2
  nhidden = 10
  init_sigma = 0.05
layer[+0] = softmax
netconfig=end
input_shape = 1,1,256
batch_size = 32
eta = 0.1
"""


def build_session(args, monitor, via: str = ""):
    """``via`` selects the boot source when both are configured:
    "artifact" (the sealed bundle), "snapshot" (--conf/--model-in),
    or "" = artifact when given, else snapshot/synthetic."""
    from cxxnet_tpu.serve import InferenceEngine, ServeSession
    from cxxnet_tpu.utils.config import parse_config, parse_config_file
    serve_pairs = [
        ("serve_buckets", args.buckets),
        ("serve_max_delay_ms", str(args.max_delay_ms)),
        ("serve_queue_rows", str(args.queue_rows)),
    ]
    if args.serve_dtype:
        serve_pairs.append(("serve_dtype", args.serve_dtype))
    if args.serve_weight_residency:
        serve_pairs.append(("serve_weight_residency",
                            args.serve_weight_residency))
    if args.artifact and via != "snapshot":
        # conf-less boot: the serve contract (bucket ladder, dtype,
        # node, max batch) comes from the sealed manifest; explicit
        # CLI knobs appended after it still win
        from cxxnet_tpu.artifact.bundle import serve_cfg_from_bundle
        cfg = serve_cfg_from_bundle(args.artifact) + serve_pairs
        return ServeSession(cfg, model_path=args.artifact,
                            monitor=monitor)
    if args.conf:
        cfg = parse_config_file(args.conf) + serve_pairs
        assert args.model_in, "--conf needs --model-in"
        return ServeSession(cfg, model_path=args.model_in,
                            monitor=monitor)
    # synthetic: random weights are fine — serving cost does not depend
    # on what the weights converged to
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.parallel import make_mesh
    cfg = parse_config(SYNTH_CONF) + serve_pairs
    trainer = NetTrainer(cfg, mesh=make_mesh(1, 1))
    trainer.init_model()
    trainer.set_monitor(monitor)
    from cxxnet_tpu.serve.bucketing import parse_buckets
    from cxxnet_tpu.serve.engine import input_dtype_for
    engine = InferenceEngine(
        trainer, buckets=parse_buckets(args.buckets, 32),
        monitor=monitor, input_dtype=input_dtype_for(args.serve_dtype))
    return ServeSession(cfg, engine=engine, monitor=monitor)


def sweep_point(args, clients, monitor, sink):
    """One sweep point = one fresh session (clean counters and
    telemetry), ``clients`` closed-loop clients, stats read back from
    the emitted records."""
    from cxxnet_tpu.monitor.schema import validate_records
    from cxxnet_tpu.serve import run_closed_loop
    sink.clear()
    session = build_session(args, monitor)
    rng = np.random.RandomState(0)
    inst = session.engine._inst_shape()
    pool = rng.uniform(0, 1, size=(256,) + inst).astype(np.float32)
    agg = run_closed_loop(session, pool, clients, args.requests,
                          args.request_rows)
    summary = session.close()
    errs = validate_records(sink.records)
    assert not errs, "schema-invalid serve telemetry: %s" % errs[:5]
    batches = [r for r in sink.records if r["event"] == "serve_batch"]
    pt = {
        "clients": clients,
        "requests_ok": agg["ok"],
        "requests_busy": agg["busy"],
        "requests_error": agg["error"] + agg["timeout"],
        "rows_per_sec": round(agg["rows_per_sec"], 2),
        "latency_p50_ms": summary["latency_p50_ms"],
        "latency_p99_ms": summary["latency_p99_ms"],
        "fill_rate": round(summary["fill_rate"], 4),
        "pad_fraction": round(summary["pad_fraction"], 4),
        "batches": summary["batches"],
        "mean_rows_per_batch": round(
            summary["rows"] / max(1, summary["batches"]), 2),
        "compile_events": summary["compile_events"],
        "serve_batch_records": len(batches),
    }
    mfu = serve_mfu(sink.records, agg["rows_per_sec"],
                    args.peak_tflops)
    if mfu is not None:
        pt["mfu"] = mfu
    if args.device_mem:
        # per-model resident device bytes from the weight_residency
        # record the freeze emitted during this point's session build
        res = [r for r in sink.records
               if r["event"] == "weight_residency"]
        pt["device_mem_bytes"] = res[-1]["bytes"] if res else 0
        if res:
            pt["residency_quantize_ms"] = round(
                res[-1]["quantize_ms"], 3)
    return pt


def measure_cold_start(args, monitor, sink, via):
    """Cold-start column: boot a FRESH session (load + program
    acquisition + warmup) and time to the first served reply, with
    the compile count over the whole window read from the telemetry
    stream — the artifact win lands in a bench record, not a claim.
    ``via`` = "artifact" boots the sealed bundle, "snapshot" the
    --conf/--model-in pair (the re-compile baseline column)."""
    sink.clear()
    t0 = time.perf_counter()
    session = build_session(args, monitor, via=via)
    boot_s = time.perf_counter() - t0
    inst = session.engine._inst_shape()
    t1 = time.perf_counter()
    session.predict(np.zeros((1,) + inst, np.float32))
    first_reply_ms = (time.perf_counter() - t1) * 1e3
    session.close()
    compiles = [r for r in sink.records if r["event"] == "compile"]
    art = next((r for r in sink.records
                if r["event"] == "artifact_load"), None)
    col = {
        "via": via,
        "source": args.artifact if via == "artifact"
        else args.model_in,
        "boot_s": round(boot_s, 3),
        "first_reply_ms": round(first_reply_ms, 3),
        "time_to_first_reply_s": round(boot_s + first_reply_ms / 1e3,
                                       3),
        "compile_events": len(compiles),
        "warmup_programs": int(session.warmup_programs),
    }
    if art is not None:
        col["artifact_hits"] = art["hits"]
        col["artifact_rebuilds"] = art["rebuilds"]
        col["fingerprint_match"] = art["fingerprint_match"]
    return col


def serve_mfu(records, rows_per_sec, peak_tflops):
    """Serve-side MFU from telemetry: the analytic forward FLOPs ride
    in the ``model_info`` record the engine's trainer emits, so serve
    and train MFU columns come from the same denominator (bench.py's
    --peak-tflops plumbing, doc/perf_profile.md "MFU bookkeeping").
    Eval is forward-only: flops_per_example, not the 3x train count."""
    if peak_tflops <= 0:
        return None
    flops = next((r["flops_per_example"] for r in records
                  if r["event"] == "model_info"), 0.0)
    if flops <= 0:
        return None
    return round(rows_per_sec * flops / (peak_tflops * 1e12), 6)


def parse_tenants(spec):
    """``name:clients[:rate[:burst]]`` comma list -> list of dicts
    (rate 0/absent = unlimited; burst defaults to the rate)."""
    out = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) < 2:
            raise ValueError(
                "tenant spec %r must be name:clients[:rate[:burst]]"
                % entry)
        out.append({
            "tenant": parts[0],
            "clients": int(parts[1]),
            "rate": float(parts[2]) if len(parts) > 2 else 0.0,
            "burst": float(parts[3]) if len(parts) > 3
            else (float(parts[2]) if len(parts) > 2 else 0.0),
        })
    if not out:
        raise ValueError("empty --tenants spec")
    return out


def run_multi_tenant(args, monitor, sink):
    """The closed-loop multi-tenant fleet scenario: every tenant's
    clients drive the real binary-protocol front end; quotas shed the
    over-quota mix with the typed reply; stats come back from the
    schema-validated ``serve_http`` records. Returns (record,
    slo_ok, zero_recompiles)."""
    import tempfile
    import threading

    from cxxnet_tpu.monitor.schema import validate_records
    from cxxnet_tpu.serve import BinaryClient, FleetServer
    from cxxnet_tpu.utils.config import parse_config, parse_config_file

    tenants = parse_tenants(args.tenants)
    quota = ",".join("%s:%g:%g" % (t["tenant"], t["rate"], t["burst"])
                     for t in tenants if t["rate"] > 0)
    serve_pairs = [
        ("serve_buckets", args.buckets),
        ("serve_max_delay_ms", str(args.max_delay_ms)),
        ("serve_queue_rows", str(args.queue_rows)),
        ("serve_dtype", args.serve_dtype or "float32"),
        ("serve_http_port", "-1"),
        ("serve_binary_port", "0"),
        ("serve_swap_poll_s", "0"),
    ]
    if quota:
        serve_pairs.append(("serve_quota", quota))
    sink.clear()
    with tempfile.TemporaryDirectory() as td:
        if args.conf:
            assert args.model_in, "--conf needs --model-in"
            cfg = parse_config_file(args.conf)
            model_src = args.model_in
        else:
            from cxxnet_tpu.nnet.trainer import NetTrainer
            from cxxnet_tpu.parallel import make_mesh
            cfg = parse_config(SYNTH_CONF)
            trainer = NetTrainer(cfg, mesh=make_mesh(1, 1))
            trainer.init_model()
            model_src = os.path.join(td, "0001.model.npz")
            trainer.save_model(model_src)
        fleet = FleetServer(
            cfg + serve_pairs + [("serve_models",
                                  "bench=%s" % model_src)],
            monitor=monitor)
        fleet.start()
        inst = fleet.router.resolve("bench").session.engine \
            ._inst_shape()
        rng = np.random.RandomState(0)
        pool = rng.uniform(0, 1, size=(256,) + inst) \
            .astype(np.float32)
        counts = {t["tenant"]: {"ok": 0, "shed": 0, "errors": 0}
                  for t in tenants}
        lock = threading.Lock()
        t0 = time.time()

        def client(tenant, ci):
            bc = BinaryClient("127.0.0.1", fleet.binary_port)
            try:
                for r in range(args.requests):
                    start = (ci * args.requests + r) \
                        * args.request_rows % 256
                    rows = np.take(
                        pool, range(start, start + args.request_rows),
                        axis=0, mode="wrap")
                    try:
                        status, _ = bc.predict(rows, tenant=tenant)
                    except Exception:
                        # dead transport (socket timeout, dropped
                        # connection): the requests this client never
                        # completed must show up as errors, not
                        # silently shrink the sample the SLO gate
                        # reads
                        with lock:
                            counts[tenant]["errors"] += \
                                args.requests - r
                        break
                    with lock:
                        if status == "ok":
                            counts[tenant]["ok"] += 1
                        elif status in ("over_quota", "busy"):
                            counts[tenant]["shed"] += 1
                        else:
                            counts[tenant]["errors"] += 1
            finally:
                bc.close()

        threads = [threading.Thread(target=client,
                                    args=(t["tenant"], ci))
                   for t in tenants for ci in range(t["clients"])]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.time() - t0
        summary = fleet.close()
    errs = validate_records(sink.records)
    assert not errs, "schema-invalid fleet telemetry: %s" % errs[:5]
    ok_lat = {}
    for r in sink.records:
        if r["event"] == "serve_http" and r["status"] == "ok":
            ok_lat.setdefault(r["tenant"], []).append(r["latency_ms"])
    rows_out, slo_ok = [], True
    for t in tenants:
        name = t["tenant"]
        c = counts[name]
        lat = sorted(ok_lat.get(name, []))

        def pct(q):
            return round(lat[min(len(lat) - 1,
                                 int(q * len(lat)))], 3) if lat else 0.0

        p99 = pct(0.99)
        total = c["ok"] + c["shed"] + c["errors"]
        breach = bool(args.slo_p99_ms and lat
                      and p99 > args.slo_p99_ms)
        slo_ok = slo_ok and not breach
        rows_out.append({
            "tenant": name, "clients": t["clients"],
            "rate": t["rate"], "burst": t["burst"],
            "requests_ok": c["ok"], "requests_shed": c["shed"],
            "requests_error": c["errors"],
            "shed_rate": round(c["shed"] / total, 4) if total else 0.0,
            "latency_p50_ms": pct(0.50), "latency_p99_ms": p99,
            "rows_per_sec": round(
                c["ok"] * args.request_rows / wall, 2),
            "slo_breach": breach,
        })
        print("# tenant=%s: %d ok / %d shed (rate %.2f), p50 %.2f ms"
              ", p99 %.2f ms%s"
              % (name, c["ok"], c["shed"], rows_out[-1]["shed_rate"],
                 rows_out[-1]["latency_p50_ms"], p99,
                 " SLO-BREACH" if breach else ""), file=sys.stderr)
    zero_recompiles = all(
        m.get("compile_events", 0) == 0
        for m in summary["models"].values())
    total_rps = sum(r["rows_per_sec"] for r in rows_out)
    rec = {
        "name": "serve_bench",
        "mode": "multi_tenant",
        "t": time.time(),
        "model": args.conf or "synthetic_mlp_256_64_10",
        "dtype": args.serve_dtype or "float32",
        "buckets": args.buckets,
        "max_delay_ms": args.max_delay_ms,
        "requests_per_client": args.requests,
        "request_rows": args.request_rows,
        "wall_s": round(wall, 2),
        "slo_p99_ms": args.slo_p99_ms,
        "slo_ok": slo_ok,
        "tenants": rows_out,
        "zero_recompiles": zero_recompiles,
        "quota": summary["quota"],
    }
    mfu = serve_mfu(sink.records, total_rps, args.peak_tflops)
    if mfu is not None:
        rec["mfu"] = mfu
    return rec, slo_ok, zero_recompiles


# -- continual train-while-serve soak (--generations) ---------------------


def _write_soak_idx(td, n=300, d=16, nclass=4, seed=0, name=""):
    """Learnable synthetic idx dataset (class k lights up image block
    k): the continual soak needs training that actually improves so
    the eval gate has something real to pass."""
    import struct
    rng = np.random.RandomState(seed)
    lab = rng.randint(0, nclass, size=(n,)).astype(np.uint8)
    img = rng.randint(0, 60, size=(n, d, d), dtype=np.uint8)
    blk = d // nclass
    for i in range(n):
        k = lab[i]
        img[i, :, k * blk:(k + 1) * blk] = np.minimum(
            img[i, :, k * blk:(k + 1) * blk] + 180, 255)
    pimg = os.path.join(td, "img%s.idx3" % name)
    plab = os.path.join(td, "lab%s.idx1" % name)
    with open(pimg, "wb") as f:
        f.write(struct.pack(">iiii", 0x803, n, d, d))
        f.write(img.tobytes())
    with open(plab, "wb") as f:
        f.write(struct.pack(">ii", 0x801, n))
        f.write(lab.tobytes())
    return pimg, plab


SOAK_NET = """
netconfig=start
layer[+1:h] = fullc:fc1
  nhidden = 32
  init_sigma = 0.05
layer[+1] = relu
layer[h->o] = fullc:fc2
  nhidden = 4
  init_sigma = 0.05
layer[+0] = softmax
netconfig=end
input_shape = 1,1,256
batch_size = 50
eta = 0.1
momentum = 0.9
metric[label] = error
"""


def run_continual_soak(args, monitor, sink):
    """``--generations N``: the continual train-while-serve
    acceptance soak (doc/continual.md). One process trains while its
    fleet serves; closed-loop binary clients hammer it across every
    hot-swap. Returns (record, clean, zero_recompiles):

    - ``clean`` is False (exit 3) on ANY dropped/failed client
      request, a generation that did not deploy+flip, or a
      non-monotone gated eval across deployed generations;
    - ``zero_recompiles`` is False (exit 1) on any post-warmup
      compile on a serving engine (swapped-in engines included).
    """
    import tempfile
    import threading

    from cxxnet_tpu.continual import ContinualLoop
    from cxxnet_tpu.io import create_iterator
    from cxxnet_tpu.monitor.schema import validate_records
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.serve import BinaryClient
    from cxxnet_tpu.utils.config import parse_config

    n_gen = int(args.generations)
    sink.clear()
    with tempfile.TemporaryDirectory() as td:
        pimg, plab = _write_soak_idx(td, n=300, name="tr")
        pimg2, plab2 = _write_soak_idx(td, n=100, seed=5, name="te")
        model_dir = os.path.join(td, "models")
        cfg = parse_config(SOAK_NET) + [
            ("continual_generations", str(n_gen)),
            ("continual_export_every", "6"),
            ("continual_gate_eps", "0.05"),
            ("continual_linger_s", "3"),
            ("serve_buckets", args.buckets if args.buckets != "auto"
             else "1,4"),
            ("serve_max_batch", "4"),
            ("serve_max_delay_ms", str(args.max_delay_ms)),
            ("serve_http_port", "-1"),
            ("serve_binary_port", "0"),
            ("serve_swap_poll_s", "30"),   # the notify() kick, not
            #                                the poll, drives swaps
            ("silent", "1"),
        ]
        batch_cfg = [("batch_size", "50"),
                     ("input_shape", "1,1,256")]
        itr_train = create_iterator(
            [("iter", "mnist"), ("path_img", pimg),
             ("path_label", plab), ("shuffle", "1"), ("silent", "1")],
            batch_cfg)
        itr_train.init()
        itr_eval = create_iterator(
            [("iter", "mnist"), ("path_img", pimg2),
             ("path_label", plab2), ("silent", "1")], batch_cfg)
        itr_eval.init()
        trainer = NetTrainer(cfg)
        trainer.set_monitor(monitor)
        trainer.init_model()

        deployed_done = threading.Event()
        ngen_seen = {"deployed": 0}

        def on_generation(rec):
            if rec.get("action") == "deployed":
                ngen_seen["deployed"] += 1
                if ngen_seen["deployed"] >= n_gen:
                    deployed_done.set()  # stop clients inside linger

        loop = ContinualLoop(
            cfg, trainer, itr_train, [("test", itr_eval)],
            model_dir=model_dir,
            path_for=lambda c: os.path.join(
                model_dir, "%04d.model.npz" % c),
            monitor=monitor, on_generation=on_generation,
            dispatch_period=3)
        summary = {}

        def run_loop():
            summary.update(loop.run())

        lt = threading.Thread(target=run_loop, name="continual-loop")
        lt.start()

        # clients come up once generation 1 boots the fleet
        deadline = time.time() + 600
        while time.time() < deadline and lt.is_alive() \
                and (loop.fleet is None or loop.fleet.binary_port <= 0):
            time.sleep(0.05)
        counts = {"ok": 0, "shed": 0}
        failures = []
        lock = threading.Lock()
        clients = []
        if loop.fleet is not None and loop.fleet.binary_port > 0:
            port = loop.fleet.binary_port
            rng = np.random.RandomState(0)
            pool = rng.rand(16, 256).astype(np.float32)

            def client(ci):
                bc = BinaryClient("127.0.0.1", port, timeout=120)
                try:
                    while not deployed_done.is_set():
                        rows = pool[(ci * 3) % 12:(ci * 3) % 12
                                    + args.request_rows]
                        try:
                            status, out = bc.predict(rows)
                        except Exception as e:
                            with lock:
                                failures.append(repr(e))
                            return
                        with lock:
                            if status == "ok":
                                counts["ok"] += 1
                            elif status in ("busy", "over_quota"):
                                counts["shed"] += 1
                            else:
                                failures.append((status, out))
                finally:
                    bc.close()

            clients = [threading.Thread(target=client, args=(i,))
                       for i in range(3)]
            for t in clients:
                t.start()
        lt.join(timeout=600)
        deployed_done.set()
        for t in clients:
            t.join(timeout=120)
        alive = lt.is_alive()

    errs = validate_records(sink.records)
    assert not errs, "schema-invalid continual telemetry: %s" % errs[:5]
    gens = [r for r in sink.records if r["event"] == "generation"]
    deployed = [r for r in gens if r["action"] == "deployed"]
    vals = [r["value"] for r in deployed]
    eps = 0.05
    monotone = all(b <= a + eps for a, b in zip(vals, vals[1:]))
    # the loop's rollup already folds every engine's final counter
    # (swapped-in engines included) exactly once — the per-record
    # swap_compile_events are point-in-time samples of the same
    # counters, not an additional total
    serve_compiles = int(summary.get("serve_compile_events", 0))
    clean = (not alive and not failures
             and len(deployed) == n_gen and monotone
             and int(summary.get("swaps", 0)) == n_gen - 1)
    rec = {
        "name": "serve_bench", "mode": "continual", "t": time.time(),
        "model": "synthetic_mlp_256_32_4",
        "generations": n_gen,
        "generations_deployed": len(deployed),
        "gate_skipped": int(summary.get("gate_skipped", 0)),
        "hot_swaps": int(summary.get("swaps", 0)),
        "train_updates": int(summary.get("updates", 0)),
        "eval_values": [round(v, 5) for v in vals],
        "eval_monotone": monotone,
        "requests_ok": counts["ok"],
        "requests_shed": counts["shed"],
        "requests_failed": len(failures),
        "wall_s": round(float(summary.get("wall_s", 0.0)), 2),
        "zero_failed_requests": not failures,
        "zero_recompiles": serve_compiles == 0,
    }
    for g in deployed:
        print("# generation %d: %s=%.4f, %s, swap compiles %d"
              % (g["generation"], g["metric"], g["value"],
                 "boot" if g.get("boot") else
                 "hot-swap %.2fs" % g.get("swap_wall_s", 0.0),
                 g.get("swap_compile_events", 0)), file=sys.stderr)
    print("# continual soak: %d/%d deployed, %d swaps, %d ok / %d "
          "shed / %d failed, monotone=%s, serve compiles %d"
          % (len(deployed), n_gen, rec["hot_swaps"], counts["ok"],
             counts["shed"], len(failures), monotone, serve_compiles),
          file=sys.stderr)
    return rec, clean, serve_compiles == 0


# -- retrieval scenario (--embed-search) ----------------------------------


EMBED_NET = """
netconfig=start
layer[+1:h] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[+1] = relu
layer[h->o] = fullc:fc2
  nhidden = 8
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,64
batch_size = 16
eta = 0.1
"""


def run_embed_search(args, monitor, sink):
    """``--embed-search``: the retrieval product closed-loop
    (doc/retrieval.md). Builds a sealed indexed bundle via
    ``task=build_index``, boots a fleet from it, and drives three
    scenarios through the binary protocol's op-suffix grammar:
    embed-only (``#embed``), search-only (``#search:k``), and the
    fanned embed->search composition (``#fsearch:k``). Returns
    (record, clean, zero_recompiles):

    - ``clean`` is False (exit 3) on ANY failed request, an invalid
      telemetry stream, or a recall spot-check that disagrees with
      the NumPy oracle over the sealed index (exact search: served
      top-k ids must match id-for-id);
    - ``zero_recompiles`` is False (exit 1) on any post-warmup
      compile — predict OR search program books, or a ``compile``
      event anywhere in the stream.
    """
    import tempfile
    import threading

    from cxxnet_tpu.artifact import bundle as ab
    from cxxnet_tpu.main import LearnTask
    from cxxnet_tpu.monitor.schema import validate_records
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.parallel import make_mesh
    from cxxnet_tpu.retrieval import EmbeddingIndex, oracle_topk
    from cxxnet_tpu.serve import BinaryClient, FleetServer
    from cxxnet_tpu.utils.config import parse_config

    sink.clear()
    n_clients = max(int(t) for t in args.clients.split(",") if t)
    with tempfile.TemporaryDirectory() as td:
        pimg, plab = _write_soak_idx(td, n=120, d=8, name="ix")
        model_dir = os.path.join(td, "models")
        os.makedirs(model_dir)
        conf = os.path.join(td, "run.conf")
        with open(conf, "w") as f:
            f.write('data = train\niter = mnist\n'
                    '  path_img = "%s"\n  path_label = "%s"\n'
                    '  silent = 1\niter = end\n%s\nmodel_dir = "%s"\n'
                    'print_step = 0\n'
                    % (pimg, plab, EMBED_NET, model_dir))
        snap = os.path.join(model_dir, "0001.model.npz")
        t = NetTrainer(parse_config(EMBED_NET), mesh=make_mesh(1, 1))
        t.init_model()
        t.save_model(snap)
        rc = LearnTask().run([conf, "task=build_index",
                              "model_in=%s" % snap,
                              "index_metric=cosine", "index_rows=96",
                              "search_k=8", "search_buckets=1,4,16"])
        assert rc == 0, "task=build_index failed"
        bundle = ab.default_bundle_path(snap)
        idx = EmbeddingIndex.deserialize(ab.read_index_member(bundle))
        k = int(ab.bundle_manifest(bundle)["index"]["k"])
        sink.clear()        # the bench stream starts at the boot

        fleet = FleetServer(parse_config(EMBED_NET) + [
            ("serve_models", "bench=%s" % bundle),
            ("serve_http_port", "-1"),
            ("serve_binary_port", "0"),
            ("serve_max_delay_ms", str(args.max_delay_ms)),
            ("silent", "1"),
        ], monitor=monitor)
        fleet.start()
        try:
            rng = np.random.RandomState(0)
            pool = rng.rand(64, 64).astype(np.float32)
            # one embed pass seeds the search-only query pool and the
            # oracle spot-check (post-warmup: already zero-compile)
            bc = BinaryClient("127.0.0.1", fleet.binary_port)
            parts = []
            for i in range(0, len(pool), 16):   # <= max_batch rows
                st, part = bc.predict(pool[i:i + 16],
                                      model="bench#embed",
                                      tenant="bench")
                assert st == "ok", part
                parts.append(np.asarray(part, np.float32))
            bc.close()
            qpool = np.concatenate(parts, axis=0)

            def drive(model, rows_pool):
                lats = []
                counts = {"ok": 0, "failed": 0}
                lock = threading.Lock()
                span = max(1, len(rows_pool) - args.request_rows + 1)

                def client(ci):
                    c = BinaryClient("127.0.0.1", fleet.binary_port,
                                     timeout=120)
                    r = np.random.RandomState(ci)
                    try:
                        for _ in range(args.requests):
                            i = r.randint(0, span)
                            rows = rows_pool[i:i + args.request_rows]
                            t0 = time.monotonic()
                            st, _ = c.predict(rows, model=model,
                                              tenant="bench")
                            dt = (time.monotonic() - t0) * 1e3
                            with lock:
                                lats.append(dt)
                                counts["ok" if st == "ok"
                                       else "failed"] += 1
                    finally:
                        c.close()

                threads = [threading.Thread(target=client, args=(i,))
                           for i in range(n_clients)]
                wall0 = time.monotonic()
                for th in threads:
                    th.start()
                for th in threads:
                    th.join()
                wall = time.monotonic() - wall0
                lats.sort()

                def pct(q):
                    return lats[min(len(lats) - 1,
                                    int(q * len(lats)))] \
                        if lats else 0.0

                return {
                    "model": model, "clients": n_clients,
                    "ok": counts["ok"], "failed": counts["failed"],
                    "rows_per_sec": round(
                        counts["ok"] * args.request_rows
                        / max(wall, 1e-9), 1),
                    "latency_p50_ms": round(pct(0.50), 3),
                    "latency_p99_ms": round(pct(0.99), 3),
                }

            points = []
            for name, model, rows_pool in (
                    ("embed_only", "bench#embed", pool),
                    ("search_only", "bench#search:%d" % k, qpool),
                    ("fanned_mix", "bench#fsearch:%d" % k, pool)):
                pt = drive(model, rows_pool)
                pt["scenario"] = name
                points.append(pt)
                print("# %s: %.1f rows/s, p50 %.2f ms, p99 %.2f ms, "
                      "%d ok / %d failed"
                      % (name, pt["rows_per_sec"],
                         pt["latency_p50_ms"], pt["latency_p99_ms"],
                         pt["ok"], pt["failed"]), file=sys.stderr)

            # recall spot-check: served ids vs the NumPy oracle over
            # the sealed index — exact search, so anything below 1.0
            # is a wrong answer, not an approximation
            bc = BinaryClient("127.0.0.1", fleet.binary_port)
            st, out = bc.predict(qpool[:16],
                                 model="bench#search:%d" % k,
                                 tenant="bench")
            bc.close()
            assert st == "ok", out
            got = np.asarray(out)[:, :k].astype(np.int64)
            oids, _ = oracle_topk(idx, qpool[:16], k)
            recall = float((got == oids).mean())

            health = fleet.health_snapshot()["model_health"][0]
            compiles = health["compile_events"] \
                + health.get("search_compile_events", 0) \
                + len([r for r in sink.records
                       if r.get("event") == "compile"])
            errs = validate_records(list(sink.records))
        finally:
            fleet.close()
    clean = all(p["failed"] == 0 for p in points) \
        and recall >= 0.999 and not errs
    rec = {
        "name": "serve_bench", "scenario": "embed_search",
        "t": time.time(),
        "requests_per_client": args.requests,
        "request_rows": args.request_rows,
        "index_rows": idx.rows, "dim": idx.dim,
        "metric": idx.metric, "k": k,
        "recall_at_k": round(recall, 4),
        "scenarios": points,
        "failed": sum(p["failed"] for p in points),
        "schema_errors": len(errs),
        "zero_recompiles": compiles == 0,
    }
    print("# embed-search: recall@%d %.3f vs oracle, %d failed, "
          "compiles %d" % (k, recall, rec["failed"], compiles),
          file=sys.stderr)
    return rec, clean, compiles == 0


# -- multi-replica fleet scenario (--replicas) ----------------------------


def _get_json(port, path):
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return json.loads(resp.read())
    finally:
        conn.close()


def _seal_bench_bundle(cfg, snapshot, monitor):
    """Seal the bench model into a bundle so every replica boots with
    zero-compile cold start — the mechanism that makes scale-out
    cheap (doc/artifacts.md), exercised instead of assumed."""
    from cxxnet_tpu.artifact.bundle import (default_bundle_path,
                                            export_bundle)
    from cxxnet_tpu.serve import ServeConfig, build_engine
    sc = ServeConfig(cfg)
    engine = build_engine(cfg, snapshot, buckets=sc.buckets,
                          max_batch=sc.max_batch, node=sc.node,
                          monitor=monitor)
    engine.warmup(warm_run=False)
    out = default_bundle_path(snapshot)
    export_bundle(engine, out, node=sc.node, monitor=monitor)
    return out


def _client_proc_main(ports, pool, n_clients, requests, request_rows,
                      base_ci, outq):
    """One driver WORKER PROCESS: n_clients closed-loop threads
    against the balancer tier (client ``ci`` pins to door
    ``ports[ci % len(ports)]`` — round-robin over a sharded front
    tier, the single port in a one-door fleet). Living in its own
    process keeps the client threads' GIL pressure out of the balancer
    process — in production clients are not the balancer's threads,
    and measuring them there charges their scheduling to the
    balancer's p99."""
    import threading

    from cxxnet_tpu.serve import BinaryClient

    counts = {"ok": 0, "shed": 0, "failed": [], "lat": []}
    lock = threading.Lock()

    def client(ci):
        lats = []
        try:
            bc = BinaryClient("127.0.0.1", ports[ci % len(ports)],
                              timeout=120)
        except OSError as e:
            with lock:
                counts["failed"].append(repr(e))
            return
        try:
            for r in range(requests):
                start = (ci * requests + r) * request_rows % 256
                rows = np.take(pool,
                               range(start, start + request_rows),
                               axis=0, mode="wrap")
                t0 = time.time()
                try:
                    status, _ = bc.predict(rows)
                except Exception as e:
                    with lock:
                        counts["failed"].append(repr(e))
                    break
                lats.append(time.time() - t0)
                with lock:
                    if status == "ok":
                        counts["ok"] += 1
                    elif status in ("busy", "over_quota"):
                        counts["shed"] += 1
                    else:
                        counts["failed"].append(status)
        finally:
            bc.close()
            with lock:
                counts["lat"].extend(lats)

    threads = [threading.Thread(target=client, args=(base_ci + i,))
               for i in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    outq.put(counts)


def _drive_fleet(ctl, pool, clients, requests, request_rows,
                 mid_traffic=None, procs=4, ports=None):
    """Closed-loop binary clients against the balancer, spread over
    a few driver WORKER PROCESSES (the clients' own thread scheduling
    must not ride the balancer process); returns per-outcome counts
    including client-side latencies. ``mid_traffic`` (optional
    callable) runs on the driver thread once traffic is established —
    the kill injector. Sheds (busy/over_quota) are back-off signals,
    not failures; anything else non-ok is a failed request. ``ports``
    overrides the target endpoints (the sharded front tier's door
    list); default is the controller's in-process balancer."""
    import multiprocessing as mp

    ctx = mp.get_context("fork")
    if ports is None:
        ports = [ctl.balancer.binary_port]
    procs = max(1, min(procs, clients))
    outq = ctx.Queue()
    share = [clients // procs + (1 if i < clients % procs else 0)
             for i in range(procs)]
    workers = []
    base = 0
    t0 = time.time()
    for i, n in enumerate(share):
        if not n:
            continue
        p = ctx.Process(target=_client_proc_main,
                        args=(list(ports), pool, n,
                              requests, request_rows, base, outq))
        p.start()
        workers.append(p)
        base += n
    if mid_traffic is not None:
        mid_traffic()
    counts = {"ok": 0, "shed": 0, "failed": [], "lat": []}
    for _ in workers:
        c = outq.get(timeout=600)
        counts["ok"] += c["ok"]
        counts["shed"] += c["shed"]
        counts["failed"].extend(c["failed"])
        counts["lat"].extend(c["lat"])
    for p in workers:
        p.join(timeout=60)
    counts["wall_s"] = time.time() - t0
    return counts


def _fleet_point_stats(sink, counts, request_rows):
    """One sweep-point row read back from the fleet_route records."""
    lat = sorted(r["latency_ms"] for r in sink.records
                 if r["event"] == "fleet_route"
                 and r["status"] == "ok")

    def pct(q):
        return round(lat[min(len(lat) - 1, int(q * len(lat)))], 3) \
            if lat else 0.0

    retries = sum(r["retries"] for r in sink.records
                  if r["event"] == "fleet_route")
    # coalesce fill: mean client requests per forwarded super-batch
    # (fleet_batch records exist only when fleet_coalesce_ms > 0)
    merged = [r for r in sink.records if r["event"] == "fleet_batch"]
    fill = round(sum(r["requests"] for r in merged)
                 / len(merged), 2) if merged else 1.0
    # CLIENT-side latency: what a caller actually waits, including
    # the socket/thread queueing BEFORE the balancer's handle() —
    # fleet_route latency starts inside handle(), so a data path
    # whose queueing happens in the coalescer (measured) would read
    # unfairly worse than one whose queueing hides in the accept/
    # scheduling path (unmeasured). The closed-loop sanity bound is
    # Little's law: mean latency = clients / throughput.
    clat = sorted(counts.get("lat", []))

    def cpct(q):
        return round(clat[min(len(clat) - 1,
                              int(q * len(clat)))] * 1e3, 3) \
            if clat else 0.0

    return {
        "client_p50_ms": cpct(0.50), "client_p99_ms": cpct(0.99),
        "requests_ok": counts["ok"], "requests_shed": counts["shed"],
        "requests_failed": len(counts["failed"]),
        "rows_per_sec": round(
            counts["ok"] * request_rows / counts["wall_s"], 2)
        if counts["wall_s"] > 0 else 0.0,
        "latency_p50_ms": pct(0.50), "latency_p99_ms": pct(0.99),
        "retries_recovered": retries,
        "coalesce_fill": fill,
        "coalesced_forwards": len(merged),
        "wall_s": round(counts["wall_s"], 2),
    }


def _fleet_fill_stats(ctl):
    """Replica-side batch economics summed over every live replica's
    /healthz model rows (cumulative batcher counters): the pad
    fraction the coalescer exists to shrink."""
    batches = batch_rows = bucket_rows = pad_rows = cap = 0
    for rep in ctl.manager.replicas():
        if not rep.alive():
            continue
        try:
            h = _get_json(rep.http_port, "/healthz")
        except (OSError, ValueError):
            continue
        for m in h.get("model_health", []):
            if "batch_rows" not in m:
                return {}          # pre-upgrade replica build
            batches += m["batches"]
            batch_rows += m["batch_rows"]
            bucket_rows += m["bucket_rows"]
            pad_rows += m["pad_rows"]
            cap += m["batches"] * m["max_batch"]
    if not batches:
        return {}
    return {
        "replica_batches": batches,
        "fill_rate": round(batch_rows / float(max(1, cap)), 4),
        "pad_fraction": round(pad_rows / float(max(1, bucket_rows)),
                              4),
    }


def _fleet_compile_events(ctl):
    """Post-warmup compile events summed over every live replica's
    /healthz — the fleet-wide zero-recompile gate."""
    total = 0
    for rep in ctl.manager.replicas():
        if not rep.alive():
            continue
        try:
            h = _get_json(rep.http_port, "/healthz")
        except (OSError, ValueError):
            continue   # died/retired between listing and probing
        total += sum(m["compile_events"]
                     for m in h.get("model_health", []))
    return total


def run_datapath_micro(ctl, pool, requests=250, clients=24):
    """Isolate the balancer→replica data path (the tier PR 13
    rebuilt): drive ONE live replica process through each forwarding
    mode at the same offered load and count rows/s + per-wire-op
    latency. The end-to-end sweep can hide this tier behind the
    balancer process's own per-request CPU on a contended host; this
    section measures the forwarding contract itself.

    - ``v1_blocking`` — the r12 path: one blocking round trip per
      in-flight request over pooled connections (a thread per
      request).
    - ``v2_pipelined`` — the same offered concurrency multiplexed
      over two ReplicaChannels (correlated frames, out-of-order
      replies).
    - ``v2_coalesced`` — the same rows as merged super-batches (the
      balancer coalescer's forward shape, 12 rows per frame).
    """
    import threading

    from cxxnet_tpu.fleet import ReplicaChannel
    from cxxnet_tpu.serve import BinaryClient

    rep = ctl.manager.replicas()[0]
    rows1 = np.ascontiguousarray(pool[:1], dtype="<f4")

    def stats(lats, nrows, wall):
        lats.sort()

        def pct(q):
            return round(lats[min(len(lats) - 1,
                                  int(q * len(lats)))] * 1e3, 3) \
                if lats else 0.0

        return {"rows_per_sec": round(nrows / wall, 2),
                "wire_p50_ms": pct(0.50), "wire_p99_ms": pct(0.99)}

    def drive(fn, nthreads):
        lats = []
        lock = threading.Lock()

        def worker(ci):
            mine = []
            fn(ci, mine)
            with lock:
                lats.extend(mine)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(nthreads)]
        t0 = time.time()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return lats, time.time() - t0

    out = {}
    # v1: blocking round trips, one connection per concurrent request
    def v1_client(ci, mine):
        bc = BinaryClient("127.0.0.1", rep.binary_port, timeout=120)
        try:
            for _ in range(requests):
                t0 = time.time()
                status, _ = bc.predict(rows1)
                assert status == "ok", status
                mine.append(time.time() - t0)
        finally:
            bc.close()

    lats, wall = drive(v1_client, clients)
    out["v1_blocking"] = dict(stats(lats, clients * requests, wall),
                              connections=clients, merge=1)

    # v2: the same offered concurrency pipelined over two channels
    chans = [ReplicaChannel("127.0.0.1", rep.binary_port, index=i)
             for i in range(2)]

    def v2_client(ci, mine):
        buf = [memoryview(rows1).cast("B")]
        for r in range(requests):
            ch = chans[(ci + r) % len(chans)]
            t0 = time.time()
            fut = ch.submit("", "", buf, 1, rows1.size, 0.0, 120.0)
            status, _ = fut.result(120)
            assert status == "ok", status
            mine.append(time.time() - t0)

    lats, wall = drive(v2_client, clients)
    out["v2_pipelined"] = dict(stats(lats, clients * requests, wall),
                               connections=len(chans), merge=1)

    # v2 coalesced: the same rows as 12-row super-batches
    merge = 12
    groups = max(1, clients // merge)
    big = np.ascontiguousarray(
        np.repeat(rows1, merge, axis=0), dtype="<f4")

    def v2_merged(ci, mine):
        buf = [memoryview(big).cast("B")]
        for r in range(requests):
            ch = chans[(ci + r) % len(chans)]
            t0 = time.time()
            fut = ch.submit("", "", buf, merge, rows1.size, 0.0,
                            120.0)
            status, _ = fut.result(120)
            assert status == "ok", status
            mine.append(time.time() - t0)

    lats, wall = drive(v2_merged, groups)
    out["v2_coalesced"] = dict(
        stats(lats, groups * requests * merge, wall),
        connections=len(chans), merge=merge)
    for ch in chans:
        ch.close()
    return out


def run_multi_replica(args, monitor, sink):
    """``--replicas N1,N2,...``: rows/s + p99 at each fleet size,
    then (at the largest size) the kill-a-replica scenario — SIGKILL
    one replica process mid-traffic, assert ZERO failed requests —
    and, with ``--autoscale-soak S``, an elasticity soak: drive load
    until the controller scales out, go idle until it drains back,
    zero dropped requests throughout."""
    import os
    import signal
    import tempfile

    from cxxnet_tpu.fleet import FleetController
    from cxxnet_tpu.monitor.schema import validate_records
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.parallel import make_mesh
    from cxxnet_tpu.utils.config import parse_config, parse_config_file

    rng = np.random.RandomState(0)
    sizes = [int(t) for t in args.replicas.split(",") if t]
    record = {"name": "serve_bench", "mode": "multi_replica",
              "t": time.time(),
              "requests_per_client": args.requests,
              "request_rows": args.request_rows,
              "buckets": args.buckets,
              "max_delay_ms": args.max_delay_ms,
              "dtype": args.serve_dtype or "float32",
              "coalesce_ms": args.coalesce_ms,
              "channels_per_replica": args.channels,
              "slo_p99_ms": args.slo_p99_ms}
    failures, recompiles = 0, 0
    # the CLI serve knobs must reach the REPLICA processes (which read
    # conf_path + these overrides), or the record would label a sweep
    # that never ran with them
    serve_overrides = [
        "serve_buckets=%s" % args.buckets,
        "serve_max_delay_ms=%g" % args.max_delay_ms,
        "serve_queue_rows=%d" % (args.queue_rows or 4096),
    ]
    if args.serve_dtype:
        serve_overrides.append("serve_dtype=%s" % args.serve_dtype)
    with tempfile.TemporaryDirectory() as td:
        if args.conf:
            assert args.model_in, "--conf needs --model-in"
            conf_path = args.conf
            cfg = parse_config_file(args.conf) + [
                (p.split("=", 1)[0], p.split("=", 1)[1])
                for p in serve_overrides]
            source = args.artifact or args.model_in
        else:
            conf_text = SYNTH_CONF + (
                "\nserve_buckets = %s\nserve_max_delay_ms = %g\n"
                "serve_queue_rows = %d\n"
                % (args.buckets, args.max_delay_ms,
                   args.queue_rows or 4096))
            if args.serve_dtype:
                conf_text += "serve_dtype = %s\n" % args.serve_dtype
            conf_path = os.path.join(td, "bench.conf")
            with open(conf_path, "w") as f:
                f.write(conf_text)
            cfg = parse_config(conf_text)
            trainer = NetTrainer(cfg, mesh=make_mesh(1, 1))
            trainer.init_model()
            snap = os.path.join(td, "0001.model.npz")
            trainer.save_model(snap)
            # replicas boot from the sealed bundle: zero-compile cold
            # start is the whole reason scale-out is cheap
            source = args.artifact or _seal_bench_bundle(cfg, snap,
                                                         monitor)
        record["model"] = os.path.basename(source)
        pool = None
        tier_base = [
            ("model_in", source),
            ("fleet_http_port", "0"), ("fleet_binary_port", "0"),
            ("fleet_health_poll_s", "0.2"),
            ("fleet_dir", os.path.join(td, "run")),
        ]

        # the data path under test (channels + coalescing); the
        # baseline sweep pins the r12 path (pooled, no coalescing)
        datapath = [
            ("fleet_channels_per_replica", str(args.channels)),
            ("fleet_coalesce_ms", "%g" % args.coalesce_ms),
        ]
        baseline_path = [
            ("fleet_channels_per_replica", "0"),
            ("fleet_coalesce_ms", "0"),
        ]

        def boot(n, extra=(), path=None):
            ctl = FleetController(
                cfg + tier_base + [("fleet_replicas", str(n)),
                                   ("fleet_min_replicas", str(n))]
                + (datapath if path is None else list(path))
                + list(extra),
                conf_path=conf_path, monitor=monitor,
                extra_overrides=serve_overrides)
            ctl.start()
            return ctl

        def one_point(n, path=None):
            nonlocal pool, recompiles
            sink.clear()
            t0 = time.time()
            ctl = boot(n, path=path)
            boot_s = time.time() - t0
            if pool is None:
                inst = tuple(_get_json(
                    ctl.manager.replicas()[0].http_port,
                    "/v1/models")["models"][0]["instance_shape"])
                pool = rng.uniform(0, 1, size=(256,) + inst) \
                    .astype(np.float32)
            cpr = args.fleet_clients_per_replica
            counts = _drive_fleet(ctl, pool, clients=cpr * n,
                                  requests=args.requests,
                                  request_rows=args.request_rows)
            recompiles += _fleet_compile_events(ctl)
            fill = _fleet_fill_stats(ctl)
            ctl.close()
            errs = validate_records(sink.records)
            assert not errs, "schema-invalid fleet telemetry: %s" \
                % errs[:5]
            return dict(_fleet_point_stats(sink, counts,
                                           args.request_rows),
                        replicas=n, clients=cpr * n,
                        boot_s=round(boot_s, 2), **fill)

        sweep = []
        for n in sizes:
            pt = one_point(n)
            failures += pt["requests_failed"]
            sweep.append(pt)
            print("# replicas=%d: %.1f rows/s, client p50 %.2f ms "
                  "p99 %.2f ms, %d ok / %d failed, coalesce fill "
                  "%.2f, pad %.3f"
                  % (n, pt["rows_per_sec"], pt["client_p50_ms"],
                     pt["client_p99_ms"], pt["requests_ok"],
                     pt["requests_failed"], pt["coalesce_fill"],
                     pt.get("pad_fraction", -1)), file=sys.stderr)
        record["sweep"] = sweep

        if args.fleet_baseline:
            # before/after on the same model and drive: the r12 data
            # path (pooled connections, no coalescing) per fleet size
            base = []
            for n in sizes:
                pt = one_point(n, path=baseline_path)
                failures += pt["requests_failed"]
                base.append(pt)
                print("# baseline replicas=%d: %.1f rows/s, client "
                      "p50 %.2f ms p99 %.2f ms, pad %.3f"
                      % (n, pt["rows_per_sec"], pt["client_p50_ms"],
                         pt["client_p99_ms"],
                         pt.get("pad_fraction", -1)),
                      file=sys.stderr)
            record["sweep_baseline"] = base

        # -- data-path micro: the balancer→replica tier isolated -----
        ctl = boot(1)
        record["datapath_micro"] = run_datapath_micro(
            ctl, pool, requests=min(args.requests, 250))
        ctl.close()
        for mode, m in record["datapath_micro"].items():
            print("# datapath %-13s %8.1f rows/s, wire p50 %.2f ms "
                  "p99 %.2f ms (merge=%d over %d conns)"
                  % (mode, m["rows_per_sec"], m["wire_p50_ms"],
                     m["wire_p99_ms"], m["merge"], m["connections"]),
                  file=sys.stderr)

        # -- kill-a-replica mid-traffic (at the largest fleet) -------
        sink.clear()
        n = max(sizes)
        ctl = boot(n, extra=[("fleet_scale_interval_s", "0.2")])

        def killer():
            time.sleep(0.3)           # let traffic establish
            victim = ctl.manager.replicas()[0]
            os.kill(victim.pid, signal.SIGKILL)
            print("# killed replica %s (pid %d) mid-traffic"
                  % (victim.replica_id, victim.pid), file=sys.stderr)

        counts = _drive_fleet(
            ctl, pool, clients=args.fleet_clients_per_replica * n,
            requests=args.requests, request_rows=args.request_rows,
            mid_traffic=killer)
        healed = sum(1 for r in ctl.manager.replicas()
                     if r.alive()) >= n
        recompiles += _fleet_compile_events(ctl)
        ctl.close()
        kill_pt = _fleet_point_stats(sink, counts, args.request_rows)
        kill_pt.update({
            "replicas": n, "replica_killed": True,
            "self_healed": healed,
            "replica_lost_events": sum(
                1 for r in sink.records
                if r["event"] == "fleet_scale"
                and r["action"] == "replica_lost"),
        })
        failures += kill_pt["requests_failed"]
        record["kill_replica"] = kill_pt
        print("# kill-a-replica: %d ok / %d failed, %d retries "
              "recovered, self_healed=%s"
              % (kill_pt["requests_ok"], kill_pt["requests_failed"],
                 kill_pt["retries_recovered"], healed),
              file=sys.stderr)

        # -- autoscale soak ------------------------------------------
        if args.autoscale_soak > 0:
            sink.clear()
            ctl = boot(1, extra=[
                ("fleet_min_replicas", "1"),
                ("fleet_max_replicas", str(max(2, max(sizes)))),
                ("fleet_scale_interval_s", "0.3"),
                ("fleet_scale_up_after_s", "0.6"),
                ("fleet_scale_down_after_s", "1.5"),
            ])
            import threading
            stop = threading.Event()
            soak = {"ok": 0, "shed": 0, "failed": []}
            lock = threading.Lock()

            def hammer(ci):
                from cxxnet_tpu.serve import BinaryClient
                bc = BinaryClient("127.0.0.1",
                                  ctl.balancer.binary_port,
                                  timeout=120)
                try:
                    while not stop.is_set():
                        rows = pool[(ci * 7) % 128:
                                    (ci * 7) % 128 + 8]
                        try:
                            status, _ = bc.predict(rows)
                        except Exception as e:
                            with lock:
                                soak["failed"].append(repr(e))
                            return
                        with lock:
                            if status == "ok":
                                soak["ok"] += 1
                            elif status in ("busy", "over_quota"):
                                soak["shed"] += 1
                            else:
                                soak["failed"].append(status)
                finally:
                    bc.close()

            threads = [threading.Thread(target=hammer, args=(i,))
                       for i in range(16)]
            for t in threads:
                t.start()
            deadline = time.time() + args.autoscale_soak

            def saw(action):
                return any(r["event"] == "fleet_scale"
                           and r["action"] == action
                           for r in sink.records)

            while time.time() < deadline and not saw("scale_out"):
                time.sleep(0.2)
            scaled_out = saw("scale_out")
            stop.set()
            for t in threads:
                t.join()
            deadline = time.time() + args.autoscale_soak
            while time.time() < deadline and not saw("scale_in"):
                time.sleep(0.2)
            recompiles += _fleet_compile_events(ctl)
            ctl.close()
            record["autoscale"] = {
                "scaled_out": scaled_out, "scaled_in": saw("scale_in"),
                "requests_ok": soak["ok"],
                "requests_shed": soak["shed"],
                "requests_failed": len(soak["failed"]),
                "max_ready_seen": max(
                    (r["ready"] for r in sink.records
                     if r["event"] == "fleet_scale"), default=1),
            }
            failures += len(soak["failed"])
            if not (scaled_out and record["autoscale"]["scaled_in"]):
                failures += 1          # the soak's own assertion
            print("# autoscale soak: out=%s in=%s, %d ok / %d shed "
                  "/ %d failed"
                  % (scaled_out, record["autoscale"]["scaled_in"],
                     soak["ok"], soak["shed"],
                     len(soak["failed"])), file=sys.stderr)
    slo_ok = all(p["latency_p99_ms"] <= args.slo_p99_ms
                 for p in sweep) if args.slo_p99_ms else True
    record["slo_ok"] = slo_ok
    record["zero_recompiles"] = recompiles == 0
    record["zero_failed_requests"] = failures == 0
    return record, failures == 0 and slo_ok, recompiles == 0


# -- sharded front tier scenario (--balancers) -----------------------------


def _null_replica_main(port_file):
    """A no-engine fleet replica for FRONT-TIER isolation: answers
    both binary protocol versions instantly (ok, one float per row)
    and ``/healthz`` with a healthy body. Driving N doors over null
    replicas measures the balancer tier itself — frame parse, quota
    admit, route, forward — with model dispatch taken out of the
    denominator (the ``run_datapath_micro`` methodology applied one
    tier up)."""
    import socket
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from cxxnet_tpu.fleet.placement import write_endpoint_file
    from cxxnet_tpu.serve.frontend import (BIN_MAGIC_V2, STATUS_OK,
                                           _REQ_HEADER,
                                           _REQ_HEADER_V2, _read_exact,
                                           pack_reply, pack_reply_v2)

    class _Health(BaseHTTPRequestHandler):
        def do_GET(self):
            body = json.dumps({"ok": 1, "queue_rows": 0,
                               "model_health": []}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):   # no access log
            pass

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _Health)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()

    lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(128)

    def serve_conn(sock):
        rfile = sock.makefile("rb")
        try:
            while True:
                magic = _read_exact(rfile, 4)
                if magic is None or len(magic) < 4:
                    return
                if magic == BIN_MAGIC_V2:
                    rest = _read_exact(rfile, _REQ_HEADER_V2.size - 4)
                    if rest is None \
                            or len(rest) < _REQ_HEADER_V2.size - 4:
                        return
                    (_, corr, ml, tl, nrows, elems,
                     _t) = _REQ_HEADER_V2.unpack(magic + rest)
                else:
                    rest = _read_exact(rfile, _REQ_HEADER.size - 4)
                    if rest is None \
                            or len(rest) < _REQ_HEADER.size - 4:
                        return
                    (_, ml, tl, nrows, elems,
                     _t) = _REQ_HEADER.unpack(magic + rest)
                    corr = None
                if ml + tl:
                    _read_exact(rfile, ml + tl)
                if nrows * elems:
                    _read_exact(rfile, nrows * elems * 4)
                if corr is None:
                    sock.sendall(pack_reply(
                        STATUS_OK, np.zeros((nrows, 1), "<f4")))
                elif nrows == 0:
                    sock.sendall(pack_reply_v2(corr, STATUS_OK))
                else:
                    sock.sendall(pack_reply_v2(
                        corr, STATUS_OK, np.zeros((nrows, 1), "<f4")))
        except (OSError, ValueError):
            return   # client went away / torn frame: drop the conn
        finally:
            try:
                sock.close()
            except OSError:
                pass  # cxxlint: disable=CXL006 -- teardown of a dead client socket; nothing to do with a close error

    def accept_loop():
        while True:
            conn, _ = lsock.accept()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=serve_conn, args=(conn,),
                             daemon=True).start()

    threading.Thread(target=accept_loop, daemon=True).start()
    write_endpoint_file(port_file, {
        "pid": os.getpid(),
        "http_port": httpd.server_address[1],
        "binary_port": lsock.getsockname()[1]})
    while True:
        time.sleep(3600)


def _spawn_null_replicas(ctx, td, n):
    """Fork ``n`` null replicas; returns [(proc, ports_dict)]."""
    import os
    nulls = []
    for i in range(n):
        pf = os.path.join(td, "null%d.ports.json" % i)
        p = ctx.Process(target=_null_replica_main, args=(pf,),
                        daemon=True)
        p.start()
        deadline = time.time() + 30
        while not os.path.exists(pf):
            assert p.is_alive(), "null replica %d died booting" % i
            assert time.time() < deadline, \
                "null replica %d: no port file" % i
            time.sleep(0.02)
        with open(pf) as f:
            nulls.append((p, json.load(f)))
    return nulls


def _boot_front_tier(td, tag, n, nulls, extra_conf="",
                     monitor_dir=""):
    """Boot ``n`` real ``task=fleet_balancer`` door processes over the
    null replicas: the bench stands in for the controller — it writes
    the endpoint registry (replicas first, then each door as it
    publishes ports) and the doors reconcile from it. Returns
    (manager, registry, doors) with every door reporting all replicas
    ready and the full peer set."""
    import os

    from cxxnet_tpu.fleet import FleetTierConfig
    from cxxnet_tpu.fleet.placement import (BalancerManager,
                                            EndpointRegistry,
                                            endpoint_entry)
    from cxxnet_tpu.utils.config import parse_config

    fleet_dir = os.path.join(td, "front_%s" % tag)
    conf_text = ("fleet_source = null-model\n"
                 "fleet_balancers = %d\n"
                 "fleet_dir = %s\n"
                 "fleet_gossip_s = 0.2\n"
                 "fleet_health_poll_s = 0.2\n" % (n, fleet_dir)) \
        + extra_conf
    conf_path = os.path.join(td, "front_%s.conf" % tag)
    with open(conf_path, "w") as f:
        f.write(conf_text)
    tier = FleetTierConfig(parse_config(conf_text))
    registry = EndpointRegistry(tier.registry_path)
    registry.write([
        endpoint_entry("r%03d" % (i + 1), "replica", "127.0.0.1",
                       ports["http_port"], ports["binary_port"],
                       version="null", pid=ports["pid"])
        for i, (_p, ports) in enumerate(nulls)])
    mgr = BalancerManager(conf_path, tier, monitor_dir=monitor_dir)
    doors = []
    try:
        for i in range(n):
            door = mgr.spawn(i)
            registry.upsert(endpoint_entry(
                door.balancer_id, "balancer", door.host,
                door.http_port, door.binary_port, pid=door.pid))
            doors.append(door)
        # doors sync the registry on a 0.2 s cadence: wait until every
        # door has polled all replicas healthy and knows its peers
        deadline = time.time() + 30
        for door in doors:
            while True:
                try:
                    h = _get_json(door.http_port, "/healthz")
                    if h.get("ready", 0) >= len(nulls) \
                            and h.get("balancers", 0) >= n:
                        break
                except (OSError, ValueError):
                    pass  # cxxlint: disable=CXL006 -- door still binding its listener; the deadline below is the real guard
                assert time.time() < deadline, \
                    "door %s never became ready" % door.balancer_id
                time.sleep(0.05)
    except BaseException:
        mgr.close()
        raise
    return mgr, registry, doors


def _front_point_stats(counts, request_rows):
    """One front-tier drive summarized from CLIENT-side counts (the
    doors are separate processes; their telemetry is captured
    separately via per-door monitor files)."""
    clat = sorted(counts.get("lat", []))

    def cpct(q):
        return round(clat[min(len(clat) - 1,
                              int(q * len(clat)))] * 1e3, 3) \
            if clat else 0.0

    return {
        "client_p50_ms": cpct(0.50), "client_p99_ms": cpct(0.99),
        "requests_ok": counts["ok"], "requests_shed": counts["shed"],
        "requests_failed": len(counts["failed"]),
        "rows_per_sec": round(
            counts["ok"] * request_rows / counts["wall_s"], 2)
        if counts["wall_s"] > 0 else 0.0,
        "wall_s": round(counts["wall_s"], 2),
    }


def _failover_proc_main(bin_eps, http_eps, pool, n_clients, requests,
                        request_rows, base_ci, outq):
    """One kill-scenario WORKER PROCESS: even clients drive the binary
    protocol, odd clients HTTP/JSON, all through the failover clients
    holding the FULL door list — a SIGKILLed door must cost a silent
    reconnect, never a failed request."""
    import threading

    from cxxnet_tpu.serve import FailoverBinaryClient, FailoverHttpClient

    counts = {"ok": 0, "shed": 0, "failed": [], "lat": [],
              "failovers": 0}
    lock = threading.Lock()

    def client(ci):
        lats = []
        http_mode = ci % 2 == 1
        # rotate the endpoint list per client so load starts spread
        # over every door — including the one about to be killed
        off = (ci // 2) % len(bin_eps)
        eps = (http_eps if http_mode else bin_eps)
        eps = eps[off:] + eps[:off]
        fc = FailoverHttpClient(eps, timeout=120) if http_mode \
            else FailoverBinaryClient(eps, timeout=120)
        try:
            for r in range(requests):
                start = (ci * requests + r) * request_rows % 256
                rows = np.take(pool,
                               range(start, start + request_rows),
                               axis=0, mode="wrap")
                t0 = time.time()
                try:
                    if http_mode:
                        code, _body = fc.predict("", "", rows)
                        status = "ok" if code == 200 else (
                            "shed" if code == 429 else "failed:%d"
                            % code)
                    else:
                        status, _ = fc.predict(rows)
                except Exception as e:
                    with lock:
                        counts["failed"].append(repr(e))
                    break
                lats.append(time.time() - t0)
                with lock:
                    if status in ("ok", "busy", "over_quota", "shed"):
                        counts["ok" if status == "ok"
                               else "shed"] += 1
                    else:
                        counts["failed"].append(status)
        finally:
            fc.close()
            with lock:
                counts["lat"].extend(lats)
                counts["failovers"] += fc.failovers

    threads = [threading.Thread(target=client, args=(base_ci + i,))
               for i in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    outq.put(counts)


def _drive_failover(doors, pool, clients, requests, request_rows,
                    mid_traffic=None, procs=2):
    """The kill drive: HTTP+binary failover clients over every door,
    spread over worker processes like ``_drive_fleet``."""
    import multiprocessing as mp

    ctx = mp.get_context("fork")
    bin_eps = [("127.0.0.1", d.binary_port) for d in doors]
    http_eps = [("127.0.0.1", d.http_port) for d in doors]
    procs = max(1, min(procs, clients))
    outq = ctx.Queue()
    share = [clients // procs + (1 if i < clients % procs else 0)
             for i in range(procs)]
    workers = []
    base = 0
    t0 = time.time()
    for n in share:
        if not n:
            continue
        p = ctx.Process(target=_failover_proc_main,
                        args=(bin_eps, http_eps, pool, n, requests,
                              request_rows, base, outq))
        p.start()
        workers.append(p)
        base += n
    if mid_traffic is not None:
        mid_traffic()
    counts = {"ok": 0, "shed": 0, "failed": [], "lat": [],
              "failovers": 0}
    for _ in workers:
        c = outq.get(timeout=600)
        for k in ("ok", "shed", "failovers"):
            counts[k] += c[k]
        counts["failed"].extend(c["failed"])
        counts["lat"].extend(c["lat"])
    for p in workers:
        p.join(timeout=60)
    counts["wall_s"] = time.time() - t0
    return counts


def _front_quota_drive(doors, pool, request_rows, duration_s):
    """Hammer tenant ``hog`` (quota'd fleet-wide) and tenant ``good``
    (unquoted) through EVERY door at once; returns per-door, per-
    tenant outcome counts plus the measured wall."""
    import threading

    from cxxnet_tpu.serve import BinaryClient

    res = {t: {d.balancer_id: {"ok": 0, "shed": 0, "failed": 0}
               for d in doors} for t in ("hog", "good")}
    lock = threading.Lock()
    rows = pool[:request_rows]
    stop_at = time.time() + duration_s

    def drive(tenant, door):
        slot = res[tenant][door.balancer_id]
        try:
            bc = BinaryClient("127.0.0.1", door.binary_port,
                              timeout=60)
        except OSError:
            with lock:
                slot["failed"] += 1
            return
        try:
            while time.time() < stop_at:
                try:
                    status, _ = bc.predict(rows, tenant=tenant)
                except Exception:
                    with lock:
                        slot["failed"] += 1
                    return
                with lock:
                    if status == "ok":
                        slot["ok"] += 1
                    elif status == "over_quota":
                        slot["shed"] += 1
                    else:
                        slot["failed"] += 1
                # realistic clients back off on a shed / pace a
                # light tenant; a shed-speed spin would just burn
                # the single CPU every process here shares
                if status != "ok" or tenant == "good":
                    time.sleep(0.02 if tenant == "good" else 0.005)
        finally:
            bc.close()

    threads = [threading.Thread(target=drive, args=(t, d))
               for d in doors
               for t in ("hog", "hog", "good")]
    t0 = time.time()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    res["wall_s"] = time.time() - t0
    return res


def _door_sheds_typed_429(door, rows):
    """POST over-quota traffic at ONE door until it answers the typed
    429 contract: status 429, JSON error=over_quota, Retry-After."""
    import http.client
    body = json.dumps({"model": "", "tenant": "hog",
                       "rows": rows.tolist()})
    for _ in range(100):
        conn = http.client.HTTPConnection("127.0.0.1", door.http_port,
                                          timeout=10)
        try:
            conn.request("POST", "/v1/predict", body,
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            payload = json.loads(resp.read() or b"{}")
            if resp.status == 429 \
                    and payload.get("error") == "over_quota" \
                    and resp.getheader("Retry-After"):
                return True
        finally:
            conn.close()
    return False


def run_front_tier(args, monitor, sink):
    """``--balancers N1,N2,...``: the sharded front tier measured in
    isolation. Each point boots N real ``task=fleet_balancer``
    processes over engine-less null replicas and drives a FIXED total
    client count round-robin across the doors — so rows/s differences
    come from sharding the tier, not from changing offered load — with
    median-of-``--front-repeats`` and a per-point spread field. At the
    largest N: the SIGKILL-a-door scenario (concurrent HTTP+binary
    failover clients, zero failed requests) and the distributed-quota
    scenario (typed 429 through every door; fleet-wide admitted rate
    bounded by the configured rate plus one rebalance window)."""
    import multiprocessing as mp
    import os
    import signal
    import tempfile

    from cxxnet_tpu.monitor.schema import read_jsonl, validate_records

    sizes = [int(t) for t in args.balancers.split(",") if t]
    repeats = max(1, args.front_repeats)
    rng = np.random.RandomState(0)
    pool = rng.uniform(0, 1, size=(256, 16)).astype(np.float32)
    rate, burst = 200.0, 40.0
    rebalance_s = 0.5
    record = {
        "name": "serve_bench", "mode": "front_tier",
        "t": time.time(),
        "requests_per_client": args.requests,
        "request_rows": args.request_rows,
        "clients_total": args.front_clients,
        "null_replicas": args.front_replicas,
        "repeats": repeats,
        "isolation": "doors forward to engine-less null replicas: "
                     "the capture measures the balancer tier (frame "
                     "parse, quota admit, route, forward), not model "
                     "dispatch",
        "caveat": "single-CPU container: all doors share one core, "
                  "so scaling gains come from splitting a fixed "
                  "client load across smaller per-process thread "
                  "sets (less GIL/scheduler contention), not from "
                  "added compute; expect noisy, sub-linear points",
    }
    failures = 0
    ctx = mp.get_context("fork")
    with tempfile.TemporaryDirectory() as td:
        nulls = _spawn_null_replicas(ctx, td, args.front_replicas)
        try:
            sweep = []
            for n in sizes:
                t0 = time.time()
                mgr, _reg, doors = _boot_front_tier(td, "n%d" % n, n,
                                                    nulls)
                boot_s = time.time() - t0
                try:
                    runs = []
                    for _ in range(repeats):
                        counts = _drive_fleet(
                            None, pool, clients=args.front_clients,
                            requests=args.requests,
                            request_rows=args.request_rows,
                            ports=[d.binary_port for d in doors])
                        runs.append(_front_point_stats(
                            counts, args.request_rows))
                finally:
                    mgr.close()
                rates = sorted(r["rows_per_sec"] for r in runs)
                mid = runs[[r["rows_per_sec"]
                            for r in runs].index(rates[len(rates)
                                                       // 2])]
                pt = dict(mid, balancers=n,
                          boot_s=round(boot_s, 2),
                          rows_per_sec=rates[len(rates) // 2],
                          rows_per_sec_runs=rates,
                          rows_per_sec_spread=round(
                              rates[-1] - rates[0], 2))
                failures += sum(r["requests_failed"] for r in runs)
                sweep.append(pt)
                print("# balancers=%d: median %.1f rows/s (spread "
                      "%.1f over %d runs), client p50 %.2f ms p99 "
                      "%.2f ms, %d ok / %d failed"
                      % (n, pt["rows_per_sec"],
                         pt["rows_per_sec_spread"], repeats,
                         pt["client_p50_ms"], pt["client_p99_ms"],
                         pt["requests_ok"], pt["requests_failed"]),
                      file=sys.stderr)
            record["sweep"] = sweep
            med = [p["rows_per_sec"] for p in sweep]
            record["rows_per_sec_monotonic"] = all(
                b > a for a, b in zip(med, med[1:]))

            # -- distributed quota + kill-a-door at the largest N ----
            n = max(sizes)
            quota_conf = ("serve_quota = hog:%g:%g\n"
                          "fleet_quota_rebalance_s = %g\n"
                          % (rate, burst, rebalance_s))
            mdir = os.path.join(td, "door_telemetry")
            os.makedirs(mdir, exist_ok=True)
            mgr, _reg, doors = _boot_front_tier(
                td, "quota", n, nulls, extra_conf=quota_conf,
                monitor_dir=mdir)
            try:
                qrows = 4
                q = _front_quota_drive(doors, pool, qrows,
                                       duration_s=6.0)
                wall = q["wall_s"]
                admitted = sum(s["ok"] for s in q["hog"].values()) \
                    * qrows
                bound = rate * (wall + rebalance_s) + burst
                # probe rows > fleet burst: no door's share can ever
                # admit it, so the FIRST well-formed answer must be
                # the typed 429 regardless of how shares rebalanced
                typed = {d.balancer_id:
                         _door_sheds_typed_429(d, pool)
                         for d in doors}
                shares = {}
                for d in doors:
                    try:
                        h = _get_json(d.http_port, "/healthz")
                        shares[d.balancer_id] = h.get("quota_shares")
                    except (OSError, ValueError):
                        shares[d.balancer_id] = None
                quota_rec = {
                    "balancers": n, "rate": rate, "burst": burst,
                    "rebalance_s": rebalance_s, "wall_s":
                    round(wall, 2),
                    "hog": {b: dict(s) for b, s in q["hog"].items()},
                    "good": {b: dict(s)
                             for b, s in q["good"].items()},
                    "admitted_rows": admitted,
                    "admitted_rows_per_sec": round(admitted / wall, 2)
                    if wall else 0.0,
                    "bound_rows": round(bound, 1),
                    "within_bound": admitted <= bound,
                    "typed_429_every_door": all(typed.values()),
                    "typed_429_by_door": typed,
                    "quota_shares": shares,
                }
                every_door_shed = all(
                    s["shed"] > 0 for s in q["hog"].values())
                good_ok = all(s["ok"] > 0 and s["failed"] == 0
                              for s in q["good"].values())
                quota_rec["hog_shed_every_door"] = every_door_shed
                quota_rec["in_quota_clean"] = good_ok
                record["distributed_quota"] = quota_rec
                if not (quota_rec["within_bound"] and every_door_shed
                        and good_ok
                        and quota_rec["typed_429_every_door"]):
                    failures += 1
                print("# quota: admitted %.1f rows/s vs bound %.1f "
                      "(rate %g + one %gs rebalance window), 429 "
                      "through every door=%s, in-quota clean=%s"
                      % (quota_rec["admitted_rows_per_sec"],
                         bound / wall if wall else 0.0, rate,
                         rebalance_s,
                         quota_rec["typed_429_every_door"], good_ok),
                      file=sys.stderr)

                # -- SIGKILL a door under HTTP+binary load ----------
                victim = doors[-1]

                def killer():
                    time.sleep(0.25)      # let traffic establish
                    os.kill(victim.pid, signal.SIGKILL)
                    print("# killed balancer %s (pid %d) mid-traffic"
                          % (victim.balancer_id, victim.pid),
                          file=sys.stderr)

                counts = _drive_failover(
                    doors, pool, clients=args.front_clients,
                    requests=args.requests,
                    request_rows=args.request_rows,
                    mid_traffic=killer if n > 1 else None)
                kill_pt = dict(
                    _front_point_stats(counts, args.request_rows),
                    balancers=n, balancer_killed=n > 1,
                    failovers=counts["failovers"])
                failures += kill_pt["requests_failed"]
                record["kill_balancer"] = kill_pt
                print("# kill-a-balancer: %d ok / %d shed / %d "
                      "failed, %d failovers"
                      % (kill_pt["requests_ok"],
                         kill_pt["requests_shed"],
                         kill_pt["requests_failed"],
                         kill_pt["failovers"]), file=sys.stderr)
            finally:
                mgr.close()
            # the doors' own telemetry streams (monitor=jsonl per
            # door): schema-validated, and the route records must
            # carry each door's balancer id
            door_events = {"fleet_route": 0, "tenant_shed": 0,
                           "quota_rebalance": 0}
            route_doors = set()
            for fn in sorted(os.listdir(mdir)):
                recs = read_jsonl(os.path.join(mdir, fn))
                errs = validate_records(recs, strict=False)
                assert not errs, \
                    "door %s emitted schema-invalid telemetry: %s" \
                    % (fn, errs[:5])
                for r in recs:
                    if r["event"] in door_events:
                        door_events[r["event"]] += 1
                    if r["event"] == "fleet_route":
                        route_doors.add(r["balancer"])
            record["door_telemetry"] = dict(
                door_events, route_balancers=sorted(route_doors),
                streams=len(os.listdir(mdir)))
        finally:
            for p, _ports in nulls:
                p.terminate()
            for p, _ports in nulls:
                p.join(timeout=10)
    record["zero_failed_requests"] = failures == 0
    record["zero_recompiles"] = True     # nothing compiles: no engines
    return record, failures == 0, True


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--clients", default="1,2,4,8",
                    help="comma list of concurrent client counts")
    ap.add_argument("--requests", type=int, default=50,
                    help="closed-loop requests per client")
    ap.add_argument("--request-rows", type=int, default=1)
    ap.add_argument("--buckets", default="auto")
    ap.add_argument("--max-delay-ms", type=float, default=2.0)
    ap.add_argument("--queue-rows", type=int, default=0)
    ap.add_argument("--conf", default="",
                    help="config file (with --model-in) instead of the "
                         "synthetic MLP")
    ap.add_argument("--model-in", default="")
    ap.add_argument("--artifact", default="",
                    help="sealed artifact bundle (task=export, "
                         "doc/artifacts.md) to boot every session "
                         "from; adds a cold-start column (time-to-"
                         "first-reply, compile count) to the record — "
                         "plus the snapshot-boot baseline column when "
                         "--conf/--model-in are also given")
    ap.add_argument("--out", default="",
                    help="also write the JSON record to this path")
    ap.add_argument("--tenants", default="",
                    help="multi-tenant scenario: comma list of "
                         "name:clients[:rate[:burst]] (rate in "
                         "rows/s; 0 = unlimited)")
    ap.add_argument("--replicas", default="",
                    help="multi-replica fleet scenario: comma list "
                         "of replica-process counts (e.g. 1,2,4); "
                         "each point boots a fleet of N task="
                         "serve_fleet processes from a sealed bundle "
                         "behind the balancer, plus a kill-a-replica-"
                         "mid-traffic assertion (zero failed "
                         "requests) at the largest N")
    ap.add_argument("--generations", type=int, default=0,
                    help="continual train-while-serve soak "
                         "(doc/continual.md): run a task=continual-"
                         "style loop for N generations with closed-"
                         "loop clients hammering the fleet across "
                         "every hot-swap; exits 3 on any dropped "
                         "request or a non-improving gated eval, 1 "
                         "on post-warmup compiles (the existing "
                         "exit-code convention)")
    ap.add_argument("--fleet-clients-per-replica", type=int,
                    default=4,
                    help="with --replicas: closed-loop clients per "
                         "replica at each sweep point (default 4, "
                         "the r12 drive; raise it for the "
                         "high-concurrency small-request regime "
                         "coalescing targets)")
    ap.add_argument("--coalesce-ms", type=float, default=0.0,
                    help="with --replicas: balancer-side coalesce "
                         "window (fleet_coalesce_ms) — same-model "
                         "requests arriving within it forward as one "
                         "super-batch; 0 = off")
    ap.add_argument("--channels", type=int, default=2,
                    help="with --replicas: multiplexed v2 channels "
                         "per replica (fleet_channels_per_replica); "
                         "0 = the pooled v1 data path")
    ap.add_argument("--balancers", default="",
                    help="comma list of front-tier sizes (e.g. 1,2,4):"
                         " boot N task=fleet_balancer processes over "
                         "engine-less null replicas and measure the "
                         "sharded front tier in isolation — fixed "
                         "total client count split across the doors, "
                         "median-of---front-repeats rows/s per point, "
                         "then kill-a-door (zero failed requests) and "
                         "distributed-quota scenarios at the largest N")
    ap.add_argument("--front-clients", type=int, default=16,
                    help="TOTAL concurrent clients for --balancers "
                         "(held fixed across front-tier sizes so the "
                         "offered load is identical at every point)")
    ap.add_argument("--front-replicas", type=int, default=2,
                    help="null replicas behind the front tier for "
                         "--balancers")
    ap.add_argument("--front-repeats", type=int, default=3,
                    help="repetitions per --balancers point; the "
                         "headline rows/s is the median and the "
                         "record carries the per-point spread")
    ap.add_argument("--fleet-baseline", action="store_true",
                    help="with --replicas: also sweep the legacy "
                         "data path (pooled connections, no "
                         "coalescing) for a before/after record")
    ap.add_argument("--autoscale-soak", type=float, default=0.0,
                    help="with --replicas: also run an autoscale "
                         "soak capped at this many seconds per "
                         "phase — load until the controller scales "
                         "out, idle until it drains back in, zero "
                         "dropped requests throughout")
    ap.add_argument("--slo-p99-ms", type=float, default=0.0,
                    help="per-tenant ok-request p99 SLO; breach "
                         "exits 3 (0 = no assertion)")
    ap.add_argument("--serve-dtype", default="",
                    choices=["", "float32", "bfloat16", "int8", "fp8"],
                    help="serve_dtype for the engine (int8/fp8 need a "
                         "task=quantize calibrated --model-in or a "
                         "quantized --artifact); the record is "
                         "dtype-tagged. Default: the artifact's "
                         "sealed dtype, else float32")
    ap.add_argument("--device-mem", action="store_true",
                    help="add a device-memory-per-model column to "
                         "every sweep point (resident bytes from the "
                         "weight_residency record) and exit 3 if "
                         "resident bytes GROW across sweep points — "
                         "a weight-residency leak guard")
    ap.add_argument("--serve-weight-residency", default="",
                    choices=["", "0", "1"],
                    help="force serve_weight_residency for the sweep "
                         "(default: the config/trainer default, 1) — "
                         "0 gives the legacy per-dispatch "
                         "fold/quantize baseline for before/after "
                         "records")
    ap.add_argument("--embed-search", action="store_true",
                    help="retrieval product scenario "
                         "(doc/retrieval.md): build an indexed "
                         "bundle via task=build_index, boot a fleet "
                         "from it, and drive embed-only / "
                         "search-only / fanned embed->search "
                         "closed loops through the binary op-suffix "
                         "grammar; the record carries rows/s + "
                         "p50/p99 per scenario and a recall "
                         "spot-check vs the NumPy oracle (exit 1 on "
                         "post-warmup compiles, 3 on failed "
                         "requests or a recall miss)")
    ap.add_argument("--peak-tflops", type=float, default=0.0,
                    help="chip peak TFLOP/s for the serve dtype; when "
                         "set, every sweep point carries an MFU column "
                         "from the model_info analytic FLOPs — "
                         "comparable with bench.py's train MFU")
    args = ap.parse_args(argv)
    if args.serve_dtype in ("int8", "fp8") and not args.conf \
            and not args.artifact:
        ap.error("--serve-dtype %s needs a task=quantize calibrated "
                 "snapshot: pass --conf/--model-in or --artifact (the "
                 "synthetic MLP has no calibration ranges)"
                 % args.serve_dtype)
    if args.artifact and args.tenants:
        ap.error("--artifact drives the closed-loop sweep; drop "
                 "--tenants (fleet configs name bundles in "
                 "serve_models instead)")
    if args.replicas and args.tenants:
        ap.error("--replicas and --tenants are separate scenarios; "
                 "run them as two invocations")
    if args.generations and (args.replicas or args.tenants
                             or args.artifact):
        ap.error("--generations is its own scenario; drop "
                 "--replicas/--tenants/--artifact")
    if args.autoscale_soak and not args.replicas:
        ap.error("--autoscale-soak needs --replicas")
    if (args.coalesce_ms or args.fleet_baseline) \
            and not args.replicas:
        ap.error("--coalesce-ms/--fleet-baseline need --replicas")
    if args.balancers and (args.replicas or args.tenants
                           or args.generations or args.artifact):
        ap.error("--balancers is its own scenario (front tier over "
                 "null replicas); drop "
                 "--replicas/--tenants/--generations/--artifact")
    if args.embed_search and (args.replicas or args.tenants
                              or args.generations or args.balancers
                              or args.artifact):
        ap.error("--embed-search is its own scenario (it builds and "
                 "seals its own indexed bundle); drop "
                 "--replicas/--tenants/--generations/--balancers/"
                 "--artifact")

    from cxxnet_tpu.monitor import MemorySink, Monitor
    import jax
    sink = MemorySink()
    monitor = Monitor(sink)
    if args.balancers:
        rec, clean, _zero = run_front_tier(args, monitor, sink)
        rec["platform"] = jax.default_backend()
        out = json.dumps(rec, sort_keys=True)
        print(out)
        if args.out:
            with open(args.out, "w") as f:
                f.write(out + "\n")
        # exit-code convention (bench.py): 3 = a request failed, a
        # door kill dropped traffic, or the quota bound was breached;
        # no engines run so recompiles cannot occur
        return 0 if clean else 3
    if args.embed_search:
        rec, clean, zero_recompiles = run_embed_search(
            args, monitor, sink)
        rec["platform"] = jax.default_backend()
        out = json.dumps(rec, sort_keys=True)
        print(out)
        if args.out:
            with open(args.out, "w") as f:
                f.write(out + "\n")
        # exit-code convention: 1 = post-warmup compiles, 3 = a
        # request failed or the recall spot-check missed the oracle
        if not zero_recompiles:
            return 1
        return 0 if clean else 3
    if args.generations:
        rec, clean, zero_recompiles = run_continual_soak(
            args, monitor, sink)
        rec["platform"] = jax.default_backend()
        out = json.dumps(rec, sort_keys=True)
        print(out)
        if args.out:
            with open(args.out, "w") as f:
                f.write(out + "\n")
        # exit-code convention: 1 = post-warmup compiles, 3 = the
        # soak dropped requests / failed a deploy / eval regressed
        if not zero_recompiles:
            return 1
        return 0 if clean else 3
    if args.replicas:
        rec, clean, zero_recompiles = run_multi_replica(
            args, monitor, sink)
        rec["platform"] = jax.default_backend()
        out = json.dumps(rec, sort_keys=True)
        print(out)
        if args.out:
            with open(args.out, "w") as f:
                f.write(out + "\n")
        # exit-code convention (bench.py): 1 = the capture itself is
        # bad (post-warmup recompiles), 2 = argparse usage, 3 = the
        # fleet dropped requests / breached its SLO / failed the soak
        if not zero_recompiles:
            return 1
        return 0 if clean else 3
    if args.tenants:
        rec, slo_ok, zero_recompiles = run_multi_tenant(
            args, monitor, sink)
        rec["platform"] = jax.default_backend()
        out = json.dumps(rec, sort_keys=True)
        print(out)
        if args.out:
            with open(args.out, "w") as f:
                f.write(out + "\n")
        # exit-code convention (bench.py): 1 = the capture itself is
        # bad (post-warmup recompiles), 2 = argparse usage, 3 = the
        # measured fleet breached its latency SLO
        if not zero_recompiles:
            return 1
        return 0 if slo_ok else 3
    rec_dtype = args.serve_dtype or "float32"
    if not args.serve_dtype and args.conf and not args.artifact:
        # the conf's serve_dtype drives the engine when the flag is
        # unset — the record tag must say what was actually measured
        # (cross-dtype rows/s comparisons are not a signal)
        from cxxnet_tpu.nnet.quantize import normalize_serve_dtype
        from cxxnet_tpu.utils.config import parse_config_file
        for k, v in parse_config_file(args.conf):
            if k == "serve_dtype":
                rec_dtype = normalize_serve_dtype(v)
    cold_start = None
    if args.artifact:
        # cold-start columns FIRST (each is a fresh boot with clean
        # telemetry); the artifact column is the headline, the
        # snapshot column (when a --conf/--model-in baseline is
        # available) is what it saves
        cold_start = [measure_cold_start(args, monitor, sink,
                                         "artifact")]
        if args.conf and args.model_in:
            cold_start.append(measure_cold_start(args, monitor, sink,
                                                 "snapshot"))
        for c in cold_start:
            print("# cold-start via %s: boot %.2fs, first reply "
                  "%.1f ms, ttfr %.2fs, compiles %d"
                  % (c["via"], c["boot_s"], c["first_reply_ms"],
                     c["time_to_first_reply_s"], c["compile_events"]),
                  file=sys.stderr)
        if not args.serve_dtype:
            from cxxnet_tpu.artifact.bundle import bundle_manifest
            rec_dtype = bundle_manifest(args.artifact)["serve_dtype"]
    points = []
    for clients in [int(t) for t in args.clients.split(",") if t]:
        t0 = time.time()
        pt = sweep_point(args, clients, monitor, sink)
        pt["wall_s"] = round(time.time() - t0, 2)
        points.append(pt)
        print("# clients=%d: %.1f rows/s, p50 %.2f ms, p99 %.2f ms, "
              "fill %.2f, compiles %d"
              % (clients, pt["rows_per_sec"], pt["latency_p50_ms"],
                 pt["latency_p99_ms"], pt["fill_rate"],
                 pt["compile_events"]), file=sys.stderr)
    rec = {
        "name": "serve_bench",
        "t": time.time(),
        "platform": jax.default_backend(),
        "model": args.artifact or args.conf
        or "synthetic_mlp_256_64_10",
        "dtype": rec_dtype,
        "buckets": args.buckets,
        "max_delay_ms": args.max_delay_ms,
        "requests_per_client": args.requests,
        "request_rows": args.request_rows,
        "sweep": points,
        "zero_recompiles": all(p["compile_events"] == 0
                               for p in points),
    }
    if cold_start is not None:
        rec["cold_start"] = cold_start
    if args.serve_weight_residency:
        rec["weight_residency"] = int(args.serve_weight_residency)
    out = json.dumps(rec, sort_keys=True)
    print(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
    if not rec["zero_recompiles"]:
        return 1
    if args.device_mem:
        # leak guard: every sweep point boots a FRESH session of the
        # same model, so its resident bytes must not grow point over
        # point — growth means freeze-time buffers leak across boots
        mem = [p["device_mem_bytes"] for p in points
               if p.get("device_mem_bytes")]
        if any(b > a for a, b in zip(mem, mem[1:])):
            print("# residency leak: resident bytes grew across "
                  "sweep points: %s" % mem, file=sys.stderr)
            return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Closed-loop serve benchmark: client sweep over the serve subsystem.

Drives N threaded closed-loop clients (each waits for its result before
sending the next request) through a ``ServeSession`` and reports one
BENCH-style JSON record on stdout: per-sweep-point request throughput,
latency p50/p99, micro-batch fill rate and pad fraction — all read back
from the schema-validated ``serve_*`` telemetry records rather than
re-derived timers (the bench.py rule), plus a ``zero_recompiles``
verdict (no XLA compile events after warmup at any sweep point).

Default is a self-contained synthetic MLP on whatever platform jax
picks (set ``JAX_PLATFORMS=cpu`` for the CPU smoke run); pass
``--conf``/``--model-in`` to sweep a real snapshot instead.

Usage::

    JAX_PLATFORMS=cpu python tools/serve_bench.py --clients 1,2,4,8
    python tools/serve_bench.py --conf run.conf --model-in 0010.model.npz
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SYNTH_CONF = """
netconfig=start
layer[+1:h] = fullc:fc1
  nhidden = 64
  init_sigma = 0.05
layer[+1] = relu
layer[h->o] = fullc:fc2
  nhidden = 10
  init_sigma = 0.05
layer[+0] = softmax
netconfig=end
input_shape = 1,1,256
batch_size = 32
eta = 0.1
"""


def build_session(args, monitor):
    from cxxnet_tpu.serve import InferenceEngine, ServeSession
    from cxxnet_tpu.utils.config import parse_config, parse_config_file
    serve_pairs = [
        ("serve_buckets", args.buckets),
        ("serve_max_delay_ms", str(args.max_delay_ms)),
        ("serve_queue_rows", str(args.queue_rows)),
    ]
    if args.conf:
        cfg = parse_config_file(args.conf) + serve_pairs
        assert args.model_in, "--conf needs --model-in"
        return ServeSession(cfg, model_path=args.model_in,
                            monitor=monitor)
    # synthetic: random weights are fine — serving cost does not depend
    # on what the weights converged to
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.parallel import make_mesh
    cfg = parse_config(SYNTH_CONF) + serve_pairs
    trainer = NetTrainer(cfg, mesh=make_mesh(1, 1))
    trainer.init_model()
    trainer.set_monitor(monitor)
    from cxxnet_tpu.serve.bucketing import parse_buckets
    engine = InferenceEngine(
        trainer, buckets=parse_buckets(args.buckets, 32),
        monitor=monitor)
    return ServeSession(cfg, engine=engine, monitor=monitor)


def sweep_point(args, clients, monitor, sink):
    """One sweep point = one fresh session (clean counters and
    telemetry), ``clients`` closed-loop clients, stats read back from
    the emitted records."""
    from cxxnet_tpu.monitor.schema import validate_records
    from cxxnet_tpu.serve import run_closed_loop
    sink.clear()
    session = build_session(args, monitor)
    rng = np.random.RandomState(0)
    inst = session.engine._inst_shape()
    pool = rng.uniform(0, 1, size=(256,) + inst).astype(np.float32)
    agg = run_closed_loop(session, pool, clients, args.requests,
                          args.request_rows)
    summary = session.close()
    errs = validate_records(sink.records)
    assert not errs, "schema-invalid serve telemetry: %s" % errs[:5]
    batches = [r for r in sink.records if r["event"] == "serve_batch"]
    return {
        "clients": clients,
        "requests_ok": agg["ok"],
        "requests_busy": agg["busy"],
        "requests_error": agg["error"] + agg["timeout"],
        "rows_per_sec": round(agg["rows_per_sec"], 2),
        "latency_p50_ms": summary["latency_p50_ms"],
        "latency_p99_ms": summary["latency_p99_ms"],
        "fill_rate": round(summary["fill_rate"], 4),
        "pad_fraction": round(summary["pad_fraction"], 4),
        "batches": summary["batches"],
        "mean_rows_per_batch": round(
            summary["rows"] / max(1, summary["batches"]), 2),
        "compile_events": summary["compile_events"],
        "serve_batch_records": len(batches),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--clients", default="1,2,4,8",
                    help="comma list of concurrent client counts")
    ap.add_argument("--requests", type=int, default=50,
                    help="closed-loop requests per client")
    ap.add_argument("--request-rows", type=int, default=1)
    ap.add_argument("--buckets", default="auto")
    ap.add_argument("--max-delay-ms", type=float, default=2.0)
    ap.add_argument("--queue-rows", type=int, default=0)
    ap.add_argument("--conf", default="",
                    help="config file (with --model-in) instead of the "
                         "synthetic MLP")
    ap.add_argument("--model-in", default="")
    ap.add_argument("--out", default="",
                    help="also write the JSON record to this path")
    args = ap.parse_args(argv)

    from cxxnet_tpu.monitor import MemorySink, Monitor
    import jax
    sink = MemorySink()
    monitor = Monitor(sink)
    points = []
    for clients in [int(t) for t in args.clients.split(",") if t]:
        t0 = time.time()
        pt = sweep_point(args, clients, monitor, sink)
        pt["wall_s"] = round(time.time() - t0, 2)
        points.append(pt)
        print("# clients=%d: %.1f rows/s, p50 %.2f ms, p99 %.2f ms, "
              "fill %.2f, compiles %d"
              % (clients, pt["rows_per_sec"], pt["latency_p50_ms"],
                 pt["latency_p99_ms"], pt["fill_rate"],
                 pt["compile_events"]), file=sys.stderr)
    rec = {
        "name": "serve_bench",
        "t": time.time(),
        "platform": jax.default_backend(),
        "model": args.conf or "synthetic_mlp_256_64_10",
        "buckets": args.buckets,
        "max_delay_ms": args.max_delay_ms,
        "requests_per_client": args.requests,
        "request_rows": args.request_rows,
        "sweep": points,
        "zero_recompiles": all(p["compile_events"] == 0
                               for p in points),
    }
    out = json.dumps(rec, sort_keys=True)
    print(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
    return 0 if rec["zero_recompiles"] else 1


if __name__ == "__main__":
    sys.exit(main())
